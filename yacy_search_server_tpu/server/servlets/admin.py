"""Admin servlets — crawl control, index control, config, performance.

Capability equivalents of the reference's admin surface (reference:
htroot/Crawler_p.java:89 — crawl start/stop; htroot/IndexControlURLs_p.java
— per-URL index inspection/deletion; htroot/IndexControlRWIs_p.java — term
index control; htroot/ConfigProperties_p.java — raw config editor;
htroot/PerformanceQueues_p.java — pipeline/busy-thread introspection;
htroot/HostBrowser.java — index browsing by host).  The `_p` suffix marks
admin-protected pages, enforced by the HTTP layer exactly as the
reference's security handler does by path.
"""

from __future__ import annotations

from ...utils.hashes import url2hash, word2hash
from ..objects import ServerObjects, escape_html, escape_json
from . import servlet


@servlet("Crawler_p")
def respond_crawler(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    if "crawlingstart" in post and post.get("crawlingURL"):
        url = post.get("crawlingURL")
        depth = post.get_int("crawlingDepth", 0)
        kwargs = {}
        if post.get("mustmatch"):
            kwargs["crawler_url_must_match"] = post.get("mustmatch")
        if post.get("mustnotmatch"):
            kwargs["crawler_url_must_not_match"] = post.get("mustnotmatch")
        try:
            profile = sb.start_crawl(url, depth=depth, **kwargs)
            prop.put("started", 1)
            prop.put("handle", profile.handle)
            prop.put("info", "")
            # record the action for replay/scheduling (WorkTables parity:
            # every admin action lands in the api table)
            from urllib.parse import quote
            replay = (f"/Crawler_p.html?crawlingstart=1&crawlingURL="
                      f"{quote(url)}&crawlingDepth={depth}")
            # the replay URL must carry the full crawl spec, or scheduled
            # re-crawls would run unfiltered
            if kwargs.get("crawler_url_must_match"):
                replay += ("&mustmatch="
                           + quote(kwargs["crawler_url_must_match"]))
            if kwargs.get("crawler_url_must_not_match"):
                replay += ("&mustnotmatch="
                           + quote(kwargs["crawler_url_must_not_match"]))
            sb.work_tables.record_api_call(
                replay, "Crawler_p", f"crawl start for {url}",
                repeat_count=post.get_int("repeat_count", 0),
                repeat_unit=post.get("repeat_unit", "days"))
        except ValueError as e:
            prop.put("started", 0)
            prop.put("info", escape_json(str(e)))
    else:
        prop.put("started", 0)
        prop.put("info", "")
    profiles = list(sb.profiles.values())
    prop.put("crawlProfiles", len(profiles))
    for i, p in enumerate(profiles):
        pre = f"crawlProfiles_{i}_"
        prop.put(pre + "handle", p.handle)
        prop.put(pre + "name", escape_json(p.name))
        prop.put(pre + "depth", p.depth)
        prop.put(pre + "eol", 1 if i < len(profiles) - 1 else 0)
    from ...crawler.frontier import StackType
    prop.put("localCrawlSize", sb.noticed.size(StackType.LOCAL))
    return prop


@servlet("Steering_p")
def respond_steering(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Shutdown/restart control (reference: htroot/Steering.java; the
    -shutdown CLI verb POSTs here, yacy.java:503-509)."""
    prop = ServerObjects()
    if post.get("shutdown"):
        # delay so this response can leave the socket first
        import threading
        threading.Timer(0.5, sb.shutdown_event.set).start()
        prop.put("info", "shutdown in 0.5s")
    elif post.get("snapshot"):
        # freeze the store tails to disk segments (bin/indexdump.sh —
        # the persisted state IS the dump in this architecture)
        sb.index.metadata.snapshot()
        sb.index.webgraph.snapshot()
        prop.put("info", "snapshot complete")
    else:
        prop.put("info", "")
    prop.put("uptime_s", int(__import__("time").time() - sb.started))
    return prop


@servlet("IndexControlURLs_p")
def respond_urlcontrol(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("found", 0)
    prop.put("deleted", 0)
    url = post.get("urlstring")
    urlhash = post.get("urlhash")
    if url and not urlhash:
        urlhash = url2hash(url).decode("ascii")
    if urlhash:
        h = urlhash.encode("ascii")
        meta = sb.index.metadata.get_by_urlhash(h)
        if meta is not None:
            prop.put("found", 1)
            prop.put("url", escape_json(meta.get("sku", "")))
            prop.put("title", escape_json(meta.get("title", "")))
            prop.put("hash", urlhash)
            prop.put("wordcount", meta.get("wordcount_i", 0))
            if "urldelete" in post:
                sb.index.remove_document(h)
                prop.put("deleted", 1)
    prop.put("urlcount", sb.index.doc_count())
    return prop


@servlet("IndexControlRWIs_p")
def respond_rwicontrol(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    word = post.get("keystring", "").strip().lower()
    prop.put("keystring", escape_json(word))
    prop.put("count", 0)
    prop.put("urls", 0)
    if word:
        th = word2hash(word)
        prop.put("keyhash", th.decode("ascii", "replace"))
        if "deleteterm" in post:
            removed = sb.index.rwi.remove_term(th)
            prop.put("deletedrefs", len(removed))
        plist = sb.index.rwi.get(th)
        prop.put("count", len(plist))
        n = min(len(plist), post.get_int("maxlisted", 25))
        prop.put("urls", n)
        for i in range(n):
            docid = int(plist.docids[i])
            meta = sb.index.get_metadata(docid)
            prop.put(f"urls_{i}_url",
                     escape_json(meta.get("sku", "") if meta else ""))
            prop.put(f"urls_{i}_eol", 1 if i < n - 1 else 0)
    prop.put("rwicount", sb.index.rwi_size())
    return prop


@servlet("ConfigProperties_p")
def respond_config(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    if post.get("key") and "set" in post:
        sb.config.set(post.get("key"), post.get("value", ""))
    keys = sorted(sb.config.keys())
    prop.put("options", len(keys))
    for i, k in enumerate(keys):
        prop.put(f"options_{i}_key", escape_json(k))
        prop.put(f"options_{i}_value", escape_json(sb.config.get(k)))
        prop.put(f"options_{i}_eol", 1 if i < len(keys) - 1 else 0)
    return prop


@servlet("PerformanceQueues_p")
def respond_queues(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    procs = [sb._parse_proc, sb._condense_proc, sb._structure_proc,
             sb._store_proc]
    prop.put("table", len(procs))
    for i, p in enumerate(procs):
        pre = f"table_{i}_"
        m = p.metrics
        prop.put(pre + "name", p.name)
        prop.put(pre + "queued", p.queue.qsize())
        prop.put(pre + "executed", m.processed)
        prop.put(pre + "errors", m.errors)
        prop.put(pre + "avgexecms", f"{m.avg_exec_ms:.3f}")
        prop.put(pre + "workers", m.workers)
        prop.put(pre + "eol", 1 if i < len(procs) - 1 else 0)
    # async-logging health: records lost to the bounded queue were
    # counted (utils/logging.py) but surfaced nowhere until ISSUE 2
    from ...utils import logging as ylog
    prop.put("log_dropped_records", ylog.dropped_count())
    threads = getattr(sb, "threads", None)
    names = threads.names() if threads else []
    prop.put("busythreads", len(names))
    for i, name in enumerate(names):
        bt = threads.get(name)
        pre = f"busythreads_{i}_"
        prop.put(pre + "name", name)
        prop.put(pre + "busycycles", bt.busy_cycles)
        prop.put(pre + "idlecycles", bt.idle_cycles)
        prop.put(pre + "errors", bt.errors)
        prop.put(pre + "eol", 1 if i < len(names) - 1 else 0)
    return prop


@servlet("HostBrowser")
def respond_hostbrowser(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    wanted = post.get("path", "").strip()
    store = sb.index.metadata
    hosts: dict[str, int] = {}
    urls: list[str] = []
    for d in range(store.capacity()):
        m = store.get(d)
        if m is None:
            continue
        h = m.get("host_s", "")
        hosts[h] = hosts.get(h, 0) + 1
        if wanted and h == wanted:
            urls.append(m.get("sku", ""))
    if not wanted:
        top = sorted(hosts.items(), key=lambda t: -t[1])
        prop.put("hosts", len(top))
        for i, (h, c) in enumerate(top):
            prop.put(f"hosts_{i}_host", escape_json(h))
            prop.put(f"hosts_{i}_count", c)
            prop.put(f"hosts_{i}_eol", 1 if i < len(top) - 1 else 0)
        prop.put("files", 0)
    else:
        prop.put("hosts", 0)
        prop.put("files", len(urls))
        for i, u in enumerate(urls):
            prop.put(f"files_{i}_url", escape_json(u))
            prop.put(f"files_{i}_eol", 1 if i < len(urls) - 1 else 0)
    return prop


# -- round-2 surface sweep (VERDICT r1 #10) ------------------------------


@servlet("Ranking_p")
def respond_ranking(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Ranking coefficient editor wired to RankingProfile (reference:
    htroot/Ranking_p.java — the 32 shift coefficients, persisted into
    config and applied to every subsequent search)."""
    from dataclasses import fields

    from ...ops.ranking import RankingProfile
    prop = ServerObjects()
    current = RankingProfile()
    ext = sb.config.get("rankingProfile.default", "")
    if ext:
        try:
            current = RankingProfile.from_external_string(ext)
        except (ValueError, KeyError):
            pass
    if post.get("reset"):
        sb.config.set("rankingProfile.default", "")
        current = RankingProfile()
        prop.put("saved", 1)
    elif post.get("save"):
        for f in fields(current):
            v = post.get(f"coeff_{f.name}", "")
            if v != "":
                try:
                    setattr(current, f.name,
                            max(0, min(15, int(v))))
                except ValueError:
                    pass
        sb.config.set("rankingProfile.default",
                      current.to_external_string())
        prop.put("saved", 1)
    coeffs = [(f.name, getattr(current, f.name))
              for f in fields(current)]
    prop.put("coeffs", len(coeffs))
    for i, (name, val) in enumerate(coeffs):
        prop.put(f"coeffs_{i}_name", name)
        prop.put(f"coeffs_{i}_value", val)
        prop.put(f"coeffs_{i}_eol", 1 if i < len(coeffs) - 1 else 0)
    return prop


@servlet("ConfigNetwork_p")
def respond_confignetwork(header: dict, post: ServerObjects,
                          sb) -> ServerObjects:
    """Network-unit selection (reference: htroot/ConfigNetwork_p.java —
    switching the network definition re-wires DHT + crawl behavior)."""
    from ...utils.config import NETWORK_UNITS
    prop = ServerObjects()
    want = post.get("unit", "").strip()
    if want:
        node = getattr(sb, "node", None)
        try:
            if node is not None:
                node.switch_network(want)
            elif want not in NETWORK_UNITS:
                raise ValueError(want)
            sb.config.set("network.unit.name", want)
            prop.put("switched", 1)
        except ValueError as e:
            prop.put("error", escape_html(str(e)))
    current = sb.config.get("network.unit.name", "freeworld")
    prop.put("current", escape_html(current))
    units = sorted(NETWORK_UNITS)
    prop.put("units", len(units))
    for i, u in enumerate(units):
        prop.put(f"units_{i}_name", u)
        prop.put(f"units_{i}_selected", 1 if u == current else 0)
        prop.put(f"units_{i}_eol", 1 if i < len(units) - 1 else 0)
    return prop


@servlet("Settings_p")
def respond_settings(header: dict, post: ServerObjects,
                     sb) -> ServerObjects:
    """General server settings (reference: htroot/Settings_p.java —
    admin account, ports, TLS, proxy and access settings in one form)."""
    prop = ServerObjects()
    editable = ("adminAccountName", "adminAccountPassword",
                "adminAccountForLocalhost", "serverClient", "port",
                "port.ssl", "server.https", "ssl.certPath", "ssl.keyPath",
                "publicSearchpage", "locale.language",
                "httpd.maxAccessPerHost.600s")
    if post.get("save"):
        for key in editable:
            v = post.get(f"set_{key}", None)
            if v is None:
                continue
            # the form round-trips the display mask for secrets; writing
            # it back would replace the real password with the mask
            if "Password" in key and v == "********":
                continue
            sb.config.set(key, v)
        prop.put("saved", 1)
    prop.put("keys", len(editable))
    for i, key in enumerate(editable):
        prop.put(f"keys_{i}_key", key)
        val = sb.config.get(key, "")
        if "Password" in key and val:
            val = "********"
        prop.put(f"keys_{i}_value", escape_html(str(val)))
        prop.put(f"keys_{i}_eol", 1 if i < len(editable) - 1 else 0)
    return prop


@servlet("User_p")
def respond_users(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """User administration (reference: htroot/User_p.java backed by
    UserDB — create/delete accounts, grant/revoke rights)."""
    from ...data.userdb import ALL_RIGHTS
    prop = ServerObjects()
    action = post.get("action", "")
    user = post.get("user", "").strip()
    if action == "create" and user:
        prop.put("created", int(sb.userdb.create(
            user, post.get("password", ""),
            [r for r in post.get("rights", "").split(",") if r])))
    elif action == "delete" and user:
        prop.put("deleted", int(sb.userdb.delete(user)))
    elif action == "grant" and user:
        prop.put("granted", int(sb.userdb.grant(user, post.get("right", ""))))
    elif action == "revoke" and user:
        prop.put("revoked", int(sb.userdb.revoke(user, post.get("right", ""))))
    rows = sb.userdb.users()
    prop.put("rights_available", ",".join(sorted(ALL_RIGHTS)))
    prop.put("users", len(rows))
    for i, row in enumerate(rows):
        prop.put(f"users_{i}_name", escape_html(row.get("name", "")))
        prop.put(f"users_{i}_rights",
                 escape_html(",".join(row.get("rights", []))))
        prop.put(f"users_{i}_eol", 1 if i < len(rows) - 1 else 0)
    return prop


@servlet("ConfigPortal_p")
def respond_configportal(header: dict, post: ServerObjects,
                         sb) -> ServerObjects:
    """Search portal appearance (reference: htroot/ConfigPortal_p.java —
    greeting, prompt, footer, result target options)."""
    prop = ServerObjects()
    keys = ("portal.greeting", "portal.prompt", "portal.footer",
            "portal.target", "portal.smallheader")
    if post.get("save"):
        for k in keys:
            v = post.get(f"set_{k}", None)
            if v is not None:
                sb.config.set(k, v)
        prop.put("saved", 1)
    for k in keys:
        prop.put(k.replace(".", "_"),
                 escape_json(sb.config.get(k, "")))
    return prop


@servlet("ConfigBasic")
def respond_configbasic(header: dict, post: ServerObjects,
                        sb) -> ServerObjects:
    """First-run basics (reference: htroot/ConfigBasic.java — peer name,
    port, use-case selection)."""
    prop = ServerObjects()
    if post.get("save"):
        # network-unit switching lives in ConfigNetwork_p, which
        # validates the unit name and re-wires the running node
        for k in ("peerName", "port"):
            v = post.get(f"set_{k}", None)
            if v is not None:
                sb.config.set(k, v)
        prop.put("saved", 1)
    prop.put("peerName", escape_json(sb.config.get("peerName", "anon")))
    prop.put("port", sb.config.get("port", "8090"))
    prop.put("doccount", sb.index.doc_count())
    return prop


@servlet("ConfigHeuristics_p")
def respond_configheuristics(header: dict, post: ServerObjects,
                             sb) -> ServerObjects:
    """Search heuristic toggles (reference: htroot/ConfigHeuristics_p.java
    — site-operator crawl and opensearch federation on/off)."""
    prop = ServerObjects()
    keys = ("heuristic.site", "heuristic.opensearch")
    if post.get("save"):
        for k in keys:
            sb.config.set(k, "true" if post.get(f"set_{k}") else "false")
        prop.put("saved", 1)
    for k in keys:
        prop.put(k.replace(".", "_"),
                 1 if sb.config.get_bool(k, False) else 0)
    return prop


@servlet("ConfigUpdate_p")
def respond_configupdate(header: dict, post: ServerObjects,
                         sb) -> ServerObjects:
    """Release/update policy (reference: htroot/ConfigUpdate_p.java —
    update location table + auto-update policy keys)."""
    prop = ServerObjects()
    if post.get("save"):
        for k in ("update.process", "update.cycle", "update.blacklist"):
            v = post.get(f"set_{k}", None)
            if v is not None:
                sb.config.set(k, v)
        prop.put("saved", 1)
    prop.put("update_process",
             escape_json(sb.config.get("update.process", "manual")))
    prop.put("update_cycle", sb.config.get("update.cycle", "168"))
    releases = []
    op = getattr(sb, "operation", None)
    if op is not None and hasattr(op, "releases"):
        releases = list(op.releases())
    prop.put("releases", len(releases))
    for i, rel in enumerate(releases):
        prop.put(f"releases_{i}_name", escape_json(str(rel)))
        prop.put(f"releases_{i}_eol", 1 if i < len(releases) - 1 else 0)
    return prop


@servlet("ConfigLanguage_p")
def respond_configlanguage(header: dict, post: ServerObjects,
                           sb) -> ServerObjects:
    """UI locale selection (reference: htroot/ConfigLanguage_p.java over
    the .lng locale files)."""
    import os as _os
    prop = ServerObjects()
    want = post.get("language", "").strip()
    if want:
        sb.config.set("locale.language", want)
        prop.put("saved", 1)
    from ..translation import shipped_languages
    current = sb.config.get("locale.language", "default")
    langs = ["default"] + shipped_languages()
    locdir = _os.path.join(sb.data_dir, "LOCALES") \
        if getattr(sb, "data_dir", None) else None
    if locdir and _os.path.isdir(locdir):
        langs += sorted(f[:-4] for f in _os.listdir(locdir)
                        if f.endswith(".lng") and f[:-4] not in langs)
    prop.put("current", escape_json(current))
    prop.put("langs", len(langs))
    for i, lg in enumerate(langs):
        prop.put(f"langs_{i}_code", escape_json(lg))
        prop.put(f"langs_{i}_selected", 1 if lg == current else 0)
        prop.put(f"langs_{i}_eol", 1 if i < len(langs) - 1 else 0)
    return prop


@servlet("CrawlStartExpert")
def respond_crawlstartexpert(header: dict, post: ServerObjects,
                             sb) -> ServerObjects:
    """Advanced crawl start (reference: htroot/CrawlStartExpert.java —
    full profile parameter surface: filters, depth, recrawl age,
    index/store toggles)."""
    prop = ServerObjects()
    url = post.get("crawlingURL", post.get("url", "")).strip()
    prop.put("started", 0)
    if url and post.get("crawlingstart"):
        kwargs = {}
        if post.get("mustmatch"):
            kwargs["crawler_url_must_match"] = post.get("mustmatch")
        if post.get("mustnotmatch"):
            kwargs["crawler_url_must_not_match"] = post.get("mustnotmatch")
        if post.get("recrawl_age_days"):
            kwargs["recrawl_if_older_s"] = \
                post.get_int("recrawl_age_days", 0) * 86400
        # toggle parsing across both client styles: machine clients send
        # explicit 0/1; HTML checkbox forms OMIT unchecked boxes, so the
        # form carries a hidden `<name>_present=1` marker — with the
        # marker, absence means unchecked
        def _toggle(name):
            v = post.get(name, None)
            if v is None:
                return not post.get(f"{name}_present")
            return v.lower() not in ("0", "false", "off")
        kwargs["index_text"] = _toggle("indexText")
        kwargs["index_media"] = _toggle("indexMedia")
        try:
            profile = sb.start_crawl(
                url, depth=post.get_int("crawlingDepth", 0),
                name=post.get("crawlingName") or None, **kwargs)
        except ValueError as e:
            prop.put("error", escape_json(str(e)))
            profile = None
        if profile is not None:
            prop.put("started", 1)
            prop.put("handle", escape_json(profile.handle))
    return prop


@servlet("CrawlProfileEditor_p")
def respond_crawlprofiles(header: dict, post: ServerObjects,
                          sb) -> ServerObjects:
    """Crawl profile registry (reference:
    htroot/CrawlProfileEditor_p.java — list + delete profiles)."""
    prop = ServerObjects()
    handle = post.get("delete", "")
    if handle:
        prop.put("deleted", int(sb.remove_profile(handle)
                                if hasattr(sb, "remove_profile")
                                else bool(sb.profiles.pop(handle, None))))
    rows = list(sb.profiles.values())
    prop.put("profiles", len(rows))
    for i, p in enumerate(rows):
        prop.put(f"profiles_{i}_handle", escape_json(p.handle))
        prop.put(f"profiles_{i}_name", escape_json(p.name))
        prop.put(f"profiles_{i}_depth", p.depth)
        prop.put(f"profiles_{i}_eol", 1 if i < len(rows) - 1 else 0)
    return prop


@servlet("IndexCleaner_p")
def respond_indexcleaner(header: dict, post: ServerObjects,
                         sb) -> ServerObjects:
    """Bulk index deletion (reference: htroot/IndexCleaner_p.java — drop
    documents by host)."""
    prop = ServerObjects()
    host = post.get("host", "").strip().lower()
    deleted = 0
    if host and post.get("run"):
        meta = sb.index.metadata
        for docid in range(meta.capacity()):
            if meta.is_deleted(docid):
                continue
            if meta.text_value(docid, "host_s") == host:
                if sb.index.remove_document(meta.urlhash_of(docid)):
                    deleted += 1
    prop.put("deleted", deleted)
    prop.put("doccount", sb.index.doc_count())
    return prop


@servlet("News")
def respond_news(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """News pool browser (reference: htroot/News.java — incoming/outgoing
    gossip records)."""
    prop = ServerObjects()
    node = getattr(sb, "node", None)
    pool = getattr(node, "news", None) if node else getattr(sb, "news", None)
    records = []
    if pool is not None:
        records = list(pool.incoming())[:post.get_int("count", 50)]
    prop.put("records", len(records))
    for i, rec in enumerate(records):
        prop.put(f"records_{i}_category", escape_json(rec.category))
        prop.put(f"records_{i}_attributes",
                 escape_json(str(rec.attributes)))
        prop.put(f"records_{i}_eol", 1 if i < len(records) - 1 else 0)
    return prop


@servlet("Surrogates_p")
def respond_surrogates(header: dict, post: ServerObjects,
                       sb) -> ServerObjects:
    """Surrogate import control (reference: htroot/IndexImportMediawiki_p
    family — list the surrogate inbox and trigger a scan)."""
    import os as _os
    prop = ServerObjects()
    indir = getattr(sb, "surrogates_in", None)
    files = sorted(_os.listdir(indir)) if indir and _os.path.isdir(indir) \
        else []
    if post.get("process"):
        n = 0
        while sb.surrogate_process_job():
            n += 1
        prop.put("processed", n)
    prop.put("files", len(files))
    for i, fn in enumerate(files):
        prop.put(f"files_{i}_name", escape_json(fn))
        prop.put(f"files_{i}_eol", 1 if i < len(files) - 1 else 0)
    return prop


@servlet("Blacklist_p")
def respond_blacklist_ui(header: dict, post: ServerObjects,
                         sb) -> ServerObjects:
    """Blacklist admin UI page (reference: htroot/Blacklist_p.java); the
    machine CRUD lives at blacklists_p, this page serves the same data
    for the UI template."""
    from .api import respond_blacklists
    return respond_blacklists(header, post, sb)
