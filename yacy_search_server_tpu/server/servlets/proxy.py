"""URL proxy servlet — browse through the node, optionally indexing.

Capability equivalent of the reference's proxy surface (reference:
source/net/yacy/http/servlets/UrlProxyServlet.java — /proxy.html?url=…
fetches the page through the node, rewrites links so navigation stays
inside the proxy, and hands the content to the indexer when
`proxyindexing` is enabled; the transparent variant lives in
server/http/HTTPDProxyHandler.java). The fetch goes through the normal
LoaderDispatcher, so the page cache, politeness and blacklist all apply.
"""

from __future__ import annotations

import re
from urllib.parse import quote, urljoin

from ...crawler.loader import CacheStrategy
from ...crawler.request import Request
from ..objects import ServerObjects
from . import servlet

_HREF_RE = re.compile(
    rb"""(\b(?:href|src|action)\s*=\s*)(["'])(.*?)\2""",
    re.IGNORECASE | re.DOTALL)


def _rewrite_html(content: bytes, base_url: str) -> bytes:
    """Point every link back through /proxy.html so navigation stays
    proxied (UrlProxyServlet's directory rewrite)."""

    def repl(m: re.Match) -> bytes:
        attr, q, target = m.group(1), m.group(2), m.group(3)
        t = target.decode("utf-8", "replace").strip()
        if t.startswith(("javascript:", "data:", "mailto:", "#")):
            return m.group(0)
        absolute = urljoin(base_url, t)
        return attr + q + f"/proxy.html?url={quote(absolute, safe='')}" \
            .encode("ascii") + q

    return _HREF_RE.sub(repl, content)


@servlet("proxy")
def respond_proxy(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    # the proxy is OFF unless the operator enables it (the reference only
    # mounts UrlProxyServlet when the proxy feature is switched on) — an
    # always-on unauthenticated fetcher would be an open SSRF surface
    if not sb.config.get_bool("proxyURL", False):
        prop.raw_body = "<b>proxy: disabled (set proxyURL=true)</b>"
        prop.raw_ctype = "text/html; charset=utf-8"
        return prop
    url = post.get("url", "")
    if not url.startswith(("http://", "https://")):
        prop.raw_body = "<b>proxy: missing or invalid url parameter</b>"
        prop.raw_ctype = "text/html; charset=utf-8"
        return prop
    if sb.blacklist.is_listed("proxy", url):
        prop.raw_body = "<b>proxy: url blocked by blacklist</b>"
        prop.raw_ctype = "text/html; charset=utf-8"
        return prop
    try:
        resp = sb.loader.load(Request(url), CacheStrategy.IFFRESH)
    except Exception as e:
        prop.raw_body = f"<b>proxy: load failed: {e}</b>"
        prop.raw_ctype = "text/html; charset=utf-8"
        return prop
    if resp.status != 200:
        prop.raw_body = f"<b>proxy: upstream status {resp.status}</b>"
        prop.raw_ctype = "text/html; charset=utf-8"
        return prop

    mime = resp.mime_type()       # parameters stripped; charset() has them
    body = resp.content
    if "html" in mime:
        body = _rewrite_html(body, url)
    # transparent indexing (HTTPDProxyHandler's proxy-crawl): hand the
    # loaded page to the indexing pipeline when enabled
    if sb.config.get_bool("proxyindexing", False):
        profile = next((p for p in sb.profiles.values()
                        if p.name == "proxy"), None)
        if profile is None:
            from ...crawler.profile import CrawlProfile
            profile = sb.add_profile(CrawlProfile(
                "proxy", store_ht_cache=True,
                recrawl_if_older_s=7 * 24 * 3600))
        sb.to_indexer(resp, profile)
    prop.raw_body = body
    if mime.startswith("text/") or "html" in mime or "xml" in mime:
        # preserve the upstream charset — re-labeling shift_jis etc. as
        # utf-8 would render mojibake
        prop.raw_ctype = f"{mime}; charset={resp.charset() or 'utf-8'}"
    else:
        prop.raw_ctype = mime
    return prop
