"""Search servlets — HTML/JSON/OpenSearch-RSS search surface + GSA XML.

Capability equivalent of the reference's search UI/API servlets
(reference: htroot/yacysearch.java:1059 — query parsing, event lookup,
result paging, template fill; htroot/yacysearch.json + yacysearch.rss
templates for the machine formats;
source/net/yacy/http/servlets/GSAsearchServlet.java for the
Google-Search-Appliance-compatible XML).  One `respond` backs all output
formats — the template chosen by extension renders the same property set.
"""

from __future__ import annotations

import time
from urllib.parse import quote

from ...utils import tracing
from ..objects import (ServerObjects, escape_html, escape_json, escape_xml)
from . import servlet


def _fill_items(prop: ServerObjects, results, esc) -> None:
    prop.put("items", len(results))
    for i, r in enumerate(results):
        p = f"items_{i}_"
        prop.put(p + "title", esc(r.title or r.url))
        prop.put(p + "link", esc(r.url))
        prop.put(p + "description", esc(r.snippet))
        prop.put(p + "urlhash", r.urlhash.decode("ascii", "replace"))
        prop.put(p + "host", esc(r.host))
        prop.put(p + "size", r.size)
        prop.put(p + "sizename", _sizename(r.size))
        prop.put(p + "ranking", int(r.score))
        prop.put(p + "source", esc(str(r.source)))
        prop.put(p + "filetype", esc(r.filetype))
        prop.put(p + "eol", 1 if i < len(results) - 1 else 0)


def _fill_image_items(prop: ServerObjects, images, esc) -> None:
    """Image-mode item properties (own result shape: the image URL plus
    source-page attribution — reference yacysearchitem.java image
    branch)."""
    prop.put("items", len(images))
    for i, im in enumerate(images):
        p = f"items_{i}_"
        prop.put(p + "image", esc(im.image_url))
        prop.put(p + "alt", esc(im.alt))
        prop.put(p + "title", esc(im.alt or im.source_title))
        prop.put(p + "link", esc(im.image_url))
        prop.put(p + "description", esc(im.alt))
        prop.put(p + "sourcelink", esc(im.source_url))
        prop.put(p + "sourcetitle", esc(im.source_title))
        prop.put(p + "urlhash",
                 im.source_urlhash.decode("ascii", "replace"))
        prop.put(p + "host", esc(im.host))
        prop.put(p + "size", 0)
        prop.put(p + "sizename", "")
        prop.put(p + "ranking", int(im.score))
        prop.put(p + "source", esc(str(im.source)))
        prop.put(p + "filetype", esc(im.filetype))
        prop.put(p + "eol", 1 if i < len(images) - 1 else 0)


def _sizename(n: int) -> str:
    for unit in ("bytes", "kB", "MB", "GB"):
        if n < 1024:
            return f"{n} {unit}"
        n //= 1024
    return f"{n} TB"


def _mod_value(prefix: str, v: str) -> str:
    """modifier:value, parenthesized when the value has whitespace (the
    parser's `prefix:(multi word)` form, query.py _strip_prefix_op)."""
    return f"{prefix}:({v})" if " " in v else f"{prefix}:{v}"


# facet dimension -> query modifier producing the refinement
# (yacysearchtrailer semantics: facet clicks append a modifier)
_FACET_MODIFIER = {
    "hosts": lambda v: _mod_value("site", v),
    "filetype": lambda v: _mod_value("filetype", v),
    "authors": lambda v: _mod_value("author", v),
    "language": lambda v: f"/language/{v}",
    "year": lambda v: f"daterange:{v}0101..{v}1231",
    "collections": lambda v: _mod_value("keyword", v),
}


def _fill_navigation(prop: ServerObjects, event, esc,
                     base_query: str = "", url_suffix: str = "") -> None:
    navs = [(name, nav.top(10)) for name, nav in event.navigators.items()
            if len(nav) > 0]
    prop.put("navigation", len(navs))
    for i, (name, entries) in enumerate(navs):
        p = f"navigation_{i}_"
        prop.put(p + "facetname", esc(name))
        prop.put(p + "elements", len(entries))
        mod = _FACET_MODIFIER.get(name)
        for j, (value, count) in enumerate(entries):
            q = f"{p}elements_{j}_"
            prop.put(q + "name", esc(str(value)))
            prop.put(q + "count", count)
            refined = (f"{base_query} {mod(value)}".strip()
                       if mod and base_query else base_query)
            prop.put(q + "url",
                     "yacysearch.html?query=" + quote(refined) + url_suffix)
            prop.put(q + "eol", 1 if j < len(entries) - 1 else 0)
        prop.put(p + "eol", 1 if i < len(navs) - 1 else 0)


def _esc_for(ext: str):
    return {"json": escape_json, "rss": escape_xml, "xml": escape_xml,
            }.get(ext, escape_html)


def _remote_fanout(sb, event, count: int) -> None:
    """Scatter to the P2P network when this switchboard belongs to a
    node (P2PNode publishes itself as sb.node) — the reference's
    resource=global search (yacysearch.java local/global resource
    param). Fired once per event: paging over the cached event must not
    re-ask the network. Delegates to P2PNode.scatter so cluster mode
    and the secondary abstract-join round behave exactly like
    node.search."""
    node = getattr(sb, "node", None)
    if node is None or event.remote_peers_asked:
        return
    with tracing.span("peers.fanout"):
        node.scatter(event, count)


@servlet("yacysearch")
def respond(header: dict, post: ServerObjects, sb) -> ServerObjects:
    with tracing.trace("servlet.yacysearch", ext=header.get("ext", "")):
        return _respond_search(header, post, sb)


def _respond_search(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    query = post.get("query", post.get("search", "")).strip()
    count = min(max(post.get_int("maximumRecords", post.get_int("count", 10)), 1), 100)
    offset = max(post.get_int("startRecord", post.get_int("offset", 0)), 0)
    ext = header.get("ext", "html")
    esc = _esc_for(ext)

    prop.put("promoteSearchPageGreeting",
             esc(sb.config.get("promoteSearchPageGreeting",
                               "YaCy TPU P2P Web Search")))
    prop.put("former", esc(query))
    prop.put("count", count)
    prop.put("offset", offset)
    prop.put("searchtime", 0)
    if not query:
        prop.put("items", 0)
        prop.put("found", 0)
        prop.put("navigation", 0)
        prop.put("totalcount", 0)
        return prop

    t0 = time.time()
    contentdom = post.get("contentdom", "").lower()
    image_mode = contentdom == "image"
    event = sb.search(query, count=count, offset=offset,
                      hybrid=post.get_bool("hybrid", False),
                      contentdom=contentdom,
                      use_cache=not post.get_bool("nocache", False),
                      dense_first=post.get_bool("densefirst", False))
    if post.get("resource", "") == "global":
        _remote_fanout(sb, event, count)
    if image_mode:
        # image serving mode: ranked pages expand into per-image entries
        # (reference SearchEvent.java:2178-2280 + the yacysearchitem
        # image branch); own item shape with source-page attribution.
        # One extra entry makes the hasnext check exact.
        images = event.image_results(offset=offset, count=count + 1)
        image_more = len(images) > count
        images = images[:count]
        results = []
        prop.put("searchtime", int((time.time() - t0) * 1000))
        prop.put("totalcount",
                 event.local_rwi_considered + event.remote_results)
        prop.put("found", 1 if images else 0)
        _fill_image_items(prop, images, esc)
    else:
        results = event.results(offset=offset, count=count)
        prop.put("searchtime", int((time.time() - t0) * 1000))
        prop.put("totalcount",
                 event.local_rwi_considered + event.remote_results)
        prop.put("found", 1 if results else 0)
        _fill_items(prop, results, esc)
    prop.put("contentdom_image", 1 if image_mode else 0)
    # page size + ranking mode must survive navigation, or page 2 would
    # re-rank differently and repeat/skip results
    suffix = f"&maximumRecords={count}"
    if post.get_bool("hybrid", False):
        suffix += "&hybrid=true"
    if post.get_bool("densefirst", False):
        # dense-first must survive paging like the hybrid flag — page 2
        # under a different retrieval mode would repeat/skip results
        suffix += "&densefirst=true"
    if contentdom:
        suffix += f"&contentdom={quote(contentdom)}"
    _fill_navigation(prop, event, esc, base_query=query, url_suffix=suffix)
    # pagination (yacysearch paging over the cached event)
    qq = quote(query)
    # content-domain tabs (the reference's Text/Images/... search tabs);
    # the hybrid flag must survive a tab switch like it survives paging
    hybrid_part = "&hybrid=true" if post.get_bool("hybrid", False) else ""
    if post.get_bool("densefirst", False):
        hybrid_part += "&densefirst=true"
    for name in ("text", "image", "audio", "video", "app"):
        prop.put(f"tab_{name}_url",
                 f"yacysearch.html?query={qq}&maximumRecords={count}"
                 f"{hybrid_part}"
                 + (f"&contentdom={name}" if name != "text" else ""))
        prop.put(f"tab_{name}_active",
                 1 if (contentdom or "text") == name else 0)
    prop.put("hasprev", 1 if offset > 0 else 0)
    prop.put("prevurl", f"yacysearch.html?query={qq}"
                        f"&startRecord={max(0, offset - count)}{suffix}")
    got_n = len(images) if image_mode else len(results)
    if image_mode:
        more = image_more
    else:
        # snippet-evicted heap slots never render: count live ones only
        more = event.results_available() > offset + got_n
    prop.put("hasnext", 1 if (more and got_n) else 0)
    prop.put("nexturl", f"yacysearch.html?query={qq}"
                        f"&startRecord={offset + count}{suffix}")
    # progressive delivery handle: the page's script can pull items
    # one-by-one from /yacysearchitem.html?eventID=...&item=N while
    # remote feeders are still filling the event
    prop.put("eventID", esc(event.query.query_id()))
    # the request's trace id: paste into Performance_Trace_p?trace=...
    # to see this exact search's waterfall
    prop.put("traceID", esc(tracing.current_trace_id() or ""))
    return prop


@servlet("yacysearchitem")
def respond_item(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """ONE result item of a cached search event, as a standalone
    fragment — progressive per-item result delivery (reference:
    htroot/yacysearchitem.java reading SearchEventCache while feeders
    run, SearchEvent.java:534-543). `item` indexes into the event's
    ranked results; remote results that arrived since the page rendered
    become visible here without re-running the query."""
    with tracing.trace("servlet.yacysearchitem"):
        return _respond_item(header, post, sb)


def _respond_item(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    eid = post.get("eventID", "")
    item = max(post.get_int("item", 0), 0)
    ext = header.get("ext", "html")
    esc = _esc_for(ext)
    prop.put("found", 0)
    prop.put("eventID", esc(eid))
    prop.put("item", item)
    ev = sb.search_cache.event_by_id(eid) if eid else None
    if ev is None:
        return prop
    rs = ev.results(offset=item, count=1)
    prop.put("total", ev.results_available())
    if not rs:
        return prop
    r = rs[0]
    prop.put("found", 1)
    prop.put("link", esc(r.url))
    prop.put("title", esc(r.title or r.url))
    prop.put("description", esc(r.snippet or ""))
    prop.put("host", esc(r.host or ""))
    prop.put("score", r.score)
    return prop


@servlet("gsasearch")
def respond_gsa(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """GSA-compatible parameter mapping: q, num, start → the same search
    (reference: GSAsearchServlet.java maps the GSA request onto an
    internal search and emits <GSP> XML)."""
    with tracing.trace("servlet.gsasearch"):
        return _respond_gsa(header, post, sb)


def _respond_gsa(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    query = post.get("q", "").strip()
    count = min(max(post.get_int("num", 10), 1), 100)
    offset = max(post.get_int("start", 0), 0)
    prop.put("q", escape_xml(query))
    prop.put("count", count)
    prop.put("offset", offset)
    if not query:
        prop.put("items", 0)
        prop.put("totalcount", 0)
        return prop
    t0 = time.time()
    event = sb.search(query, count=count, offset=offset)
    results = event.results(offset=offset, count=count)
    prop.put("searchtime", f"{time.time() - t0:.6f}")
    prop.put("totalcount", event.local_rwi_considered + event.remote_results)
    prop.put("items", len(results))
    for i, r in enumerate(results):
        p = f"items_{i}_"
        prop.put(p + "rank", offset + i + 1)
        prop.put(p + "link", escape_xml(r.url))
        prop.put(p + "title", escape_xml(r.title or r.url))
        prop.put(p + "description", escape_xml(r.snippet))
        prop.put(p + "size", r.size)
    return prop


@servlet("suggest")
def respond_suggest(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Word-completion suggestions against the indexed vocabulary
    (reference: htroot/suggest.java backed by data/DidYouMean.java)."""
    from ...search.didyoumean import DidYouMean
    prop = ServerObjects()
    q = post.get("query", post.get("q", "")).strip()
    prop.put("query", escape_json(q))
    sugg = DidYouMean(sb.index).suggest(q, count=10) if q else []
    prop.put("suggestions", len(sugg))
    for i, s in enumerate(sugg):
        prop.put(f"suggestions_{i}_word", escape_json(s))
        prop.put(f"suggestions_{i}_eol", 1 if i < len(sugg) - 1 else 0)
    return prop
