"""Performance_Health_p — the node health dashboard (ISSUE 4).

The operator surface of `utils/health.py`: the live rule table
(state / cause / evidence / since), per-histogram windowed percentiles
with a bucket-distribution sparkline, and the flight recorder's incident
list with a raw JSONL download.  The capability successor of the
reference's PerformanceQueues_p/PerformanceMemory_p pages — except the
node evaluated itself before the page was loaded."""

from __future__ import annotations

import time

from ...utils import histogram
from ..objects import ServerObjects, escape_json
from . import servlet

_SPARK = " ▁▂▃▄▅▆▇█"


def _sparkline(counts, width: int = 24) -> str:
    """Bucket-count vector -> a fixed-width unicode sparkline (the
    distribution shape at a glance; empty histogram -> all blanks)."""
    if not counts:
        return ""
    chunk = max(1, (len(counts) + width - 1) // width)
    groups = [sum(counts[i:i + chunk])
              for i in range(0, len(counts), chunk)]
    peak = max(groups)
    if peak <= 0:
        return _SPARK[0] * len(groups)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   1 + int(g / peak * (len(_SPARK) - 2)))] if g else
        _SPARK[0]
        for g in groups)


@servlet("Performance_Health_p")
def respond_health(header: dict, post: ServerObjects,
                   sb) -> ServerObjects:
    prop = ServerObjects()
    eng = getattr(sb, "health", None)
    if eng is None:
        prop.put("info", "health engine not available")
        prop.put("rules", 0)
        return prop
    # incident download: registry-name lookup only (no caller paths)
    if post.get("format", "") == "incident":
        body = eng.incident_body(post.get("name", ""))
        prop.raw_body = body if body is not None else "{}"
        prop.raw_ctype = "application/jsonl; charset=utf-8"
        return prop
    # operators (and tests) can force an evaluation pass from the page
    if post.get("tick", "") == "1":
        eng.tick()
    now = time.time()
    prop.put("overall", eng.overall())
    prop.put("status_value", eng.status_value())
    prop.put("tick_count", eng.tick_count)
    prop.put("last_tick_age_s",
             round(now - eng.last_tick, 1) if eng.last_tick else -1)
    prop.put("snapshots_retained", len(eng.snapshots))

    rows = eng.rule_table()
    prop.put("rules", len(rows))
    for i, (name, desc, st) in enumerate(rows):
        pre = f"rules_{i}_"
        prop.put(pre + "name", escape_json(name))
        prop.put(pre + "description", escape_json(desc))
        prop.put(pre + "state", st.state)
        prop.put(pre + "cause", escape_json(st.cause))
        prop.put(pre + "since_s",
                 round(now - st.since, 1) if st.since else 0.0)
        prop.put(pre + "evidence", escape_json(" ".join(
            f"{k}={v}" for k, v in st.evidence.items())))

    hists = [h for h in histogram.all_histograms()
             if h.windowed_count() > 0 or h.count > 0]
    prop.put("histograms", len(hists))
    for i, h in enumerate(hists):
        pre = f"histograms_{i}_"
        counts = h.windowed_counts()
        prop.put(pre + "name", escape_json(h.name))
        prop.put(pre + "window_count", sum(counts))
        prop.put(pre + "total_count", h.count)
        prop.put(pre + "p50_ms", round(
            histogram.percentile_from_counts(counts, 0.50), 3))
        prop.put(pre + "p95_ms", round(
            histogram.percentile_from_counts(counts, 0.95), 3))
        prop.put(pre + "p99_ms", round(
            histogram.percentile_from_counts(counts, 0.99), 3))
        prop.put(pre + "spark", _sparkline(counts))
        exes = [e for e in h.snapshot()["exemplars"] if e is not None]
        # the slowest exemplar links the family to a concrete trace
        prop.put(pre + "exemplar_trace",
                 escape_json(max(exes, key=lambda e: e[1])[0])
                 if exes else "")

    incs = list(eng.incidents)
    prop.put("incidents", len(incs))
    for i, inc in enumerate(reversed(incs)):
        pre = f"incidents_{i}_"
        prop.put(pre + "name", escape_json(inc["name"]))
        prop.put(pre + "time", int(inc["ts"]))
        prop.put(pre + "rules", escape_json(",".join(inc["rules"])))
        prop.put(pre + "file", escape_json(inc["path"] or ""))
    return prop
