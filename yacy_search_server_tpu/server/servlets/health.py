"""Performance_Health_p + Network_Health_p — node and FLEET health.

`Performance_Health_p` is the operator surface of `utils/health.py`
(ISSUE 4): the live rule table (state / cause / evidence / since),
per-histogram windowed percentiles with a bucket-distribution sparkline,
and the flight recorder's incident list with a raw JSONL download.

`Network_Health_p` is its fleet-level sibling (ISSUE 5): the per-peer
digest table (state / percentiles / staleness / seq / wire size), the
merged-vs-local histogram comparison per digest family (any node shows
the SAME eventually-consistent mesh view — no scrape coordinator), and
the fleet_* rule table.  The capability successor of the reference's
Network.html peer list — except with latency distributions instead of
just counts."""

from __future__ import annotations

import time

from ...utils import fleet as fleetmod
from ...utils import histogram
from ..objects import ServerObjects, escape_json
from . import servlet

_SPARK = " ▁▂▃▄▅▆▇█"


def _sparkline(counts, width: int = 24) -> str:
    """Bucket-count vector -> a fixed-width unicode sparkline (the
    distribution shape at a glance; empty histogram -> all blanks)."""
    if not counts:
        return ""
    chunk = max(1, (len(counts) + width - 1) // width)
    groups = [sum(counts[i:i + chunk])
              for i in range(0, len(counts), chunk)]
    peak = max(groups)
    if peak <= 0:
        return _SPARK[0] * len(groups)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   1 + int(g / peak * (len(_SPARK) - 2)))] if g else
        _SPARK[0]
        for g in groups)


@servlet("Performance_Health_p")
def respond_health(header: dict, post: ServerObjects,
                   sb) -> ServerObjects:
    prop = ServerObjects()
    eng = getattr(sb, "health", None)
    if eng is None:
        prop.put("info", "health engine not available")
        prop.put("rules", 0)
        return prop
    # incident download: registry-name lookup only (no caller paths)
    if post.get("format", "") == "incident":
        body = eng.incident_body(post.get("name", ""))
        prop.raw_body = body if body is not None else "{}"
        prop.raw_ctype = "application/jsonl; charset=utf-8"
        return prop
    # operators (and tests) can force an evaluation pass from the page
    if post.get("tick", "") == "1":
        eng.tick()
    now = time.time()
    prop.put("overall", eng.overall())
    prop.put("status_value", eng.status_value())
    prop.put("tick_count", eng.tick_count)
    prop.put("last_tick_age_s",
             round(now - eng.last_tick, 1) if eng.last_tick else -1)
    prop.put("snapshots_retained", len(eng.snapshots))

    rows = eng.rule_table()
    prop.put("rules", len(rows))
    for i, (name, desc, st) in enumerate(rows):
        pre = f"rules_{i}_"
        prop.put(pre + "name", escape_json(name))
        prop.put(pre + "description", escape_json(desc))
        prop.put(pre + "state", st.state)
        prop.put(pre + "cause", escape_json(st.cause))
        prop.put(pre + "since_s",
                 round(now - st.since, 1) if st.since else 0.0)
        prop.put(pre + "evidence", escape_json(" ".join(
            f"{k}={v}" for k, v in st.evidence.items())))

    hists = [h for h in histogram.all_histograms()
             if h.windowed_count() > 0 or h.count > 0]
    prop.put("histograms", len(hists))
    for i, h in enumerate(hists):
        pre = f"histograms_{i}_"
        counts = h.windowed_counts()
        prop.put(pre + "name", escape_json(h.name))
        prop.put(pre + "window_count", sum(counts))
        prop.put(pre + "total_count", h.count)
        prop.put(pre + "p50_ms", round(
            histogram.percentile_from_counts(counts, 0.50), 3))
        prop.put(pre + "p95_ms", round(
            histogram.percentile_from_counts(counts, 0.95), 3))
        prop.put(pre + "p99_ms", round(
            histogram.percentile_from_counts(counts, 0.99), 3))
        prop.put(pre + "spark", _sparkline(counts))
        exes = [e for e in h.snapshot()["exemplars"] if e is not None]
        # the slowest exemplar links the family to a concrete trace
        prop.put(pre + "exemplar_trace",
                 escape_json(max(exes, key=lambda e: e[1])[0])
                 if exes else "")

    incs = list(eng.incidents)
    prop.put("incidents", len(incs))
    for i, inc in enumerate(reversed(incs)):
        pre = f"incidents_{i}_"
        prop.put(pre + "name", escape_json(inc["name"]))
        prop.put(pre + "time", int(inc["ts"]))
        prop.put(pre + "rules", escape_json(",".join(inc["rules"])))
        prop.put(pre + "file", escape_json(inc["path"] or ""))

    # actuator layer (ISSUE 9): the ladder rung, each actuator's knob
    # and transition counts, and the recent breadcrumb trail — the
    # operator reads the node's DEFENSE next to its diagnosis
    act = getattr(sb, "actuators", None)
    if act is None:
        prop.put("actuators", 0)
        return prop
    from ...utils.actuator import LEVEL_NAMES
    prop.put("degrade_level", act.level)
    prop.put("degrade_name", LEVEL_NAMES[act.level])
    prop.put("actuator_ticks", act.tick_count)
    prop.put("shed_requests", act.shed_count)
    counts = act.transition_counts()
    prop.put("actuators", len(act.actuators))
    for i, a in enumerate(act.actuators):
        pre = f"actuators_{i}_"
        prop.put(pre + "name", escape_json(a.name))
        prop.put(pre + "description", escape_json(a.description))
        prop.put(pre + "knob", escape_json(a.knob))
        prop.put(pre + "down", counts.get((a.name, "down"), 0))
        prop.put(pre + "up", counts.get((a.name, "up"), 0))
    crumbs = act.recent_breadcrumbs(16)
    prop.put("breadcrumbs", len(crumbs))
    for i, c in enumerate(reversed(crumbs)):
        pre = f"breadcrumbs_{i}_"
        prop.put(pre + "time", int(c.get("ts", 0)))
        prop.put(pre + "actuator", escape_json(c.get("actuator", "")))
        prop.put(pre + "dir", escape_json(c.get("dir", "")))
        prop.put(pre + "cause", escape_json(c.get("cause", "")))
    return prop


@servlet("Network_Health_p")
def respond_network_health(header: dict, post: ServerObjects,
                           sb) -> ServerObjects:
    """The fleet dashboard (ISSUE 5): peer digest table, merged-vs-local
    percentiles per digest family, and the fleet_* rule states."""
    prop = ServerObjects()
    fl = getattr(sb, "fleet", None)
    eng = getattr(sb, "health", None)
    if fl is None:
        prop.put("info", "fleet table not available")
        prop.put("peers", 0)
        return prop
    if post.get("tick", "") == "1" and eng is not None:
        eng.tick()
    d = fl.render()
    prop.put("my_hash", escape_json(fl.my_hash))
    prop.put("gossip_enabled", 1 if fl.enabled else 0)
    prop.put("digest_seq", d.get("seq", 0))
    prop.put("digest_bytes", fl.last_digest_bytes)
    prop.put("digest_byte_budget", fl.byte_budget)
    prop.put("stale_after_s", fl.stale_s)
    prop.put("digests_received", fl.received_count)
    prop.put("digests_ignored", fl.ignored_count)

    # multi-process mesh identity (ISSUE 12): when this node is a
    # jax.distributed mesh member, the page heads with the REAL process
    # grid — its own (process id, pid) plus every peer's from the
    # gossiped digests below (the peers_N_proc_* columns)
    mm = getattr(sb, "mesh_member", None)
    import os as _os
    prop.put("mesh_member", 1 if mm is not None else 0)
    prop.put("mesh_process_id", mm.process_id if mm is not None else 0)
    prop.put("mesh_processes", mm.num_processes if mm is not None else 1)
    prop.put("mesh_pid", _os.getpid())

    rows = fl.peer_rows()
    prop.put("peers", len(rows))
    for i, r in enumerate(rows):
        pre = f"peers_{i}_"
        prop.put(pre + "hash", escape_json(r["hash"]))
        prop.put(pre + "state", r["state"])
        prop.put(pre + "age_s", r["age_s"])
        prop.put(pre + "seq", r["seq"])
        prop.put(pre + "bytes", r["bytes"])
        proc = r.get("proc") or {}
        prop.put(pre + "proc_pid", proc.get("pid", 0))
        prop.put(pre + "proc_id", proc.get("id", 0))
        prop.put(pre + "proc_lost", proc.get("lost", 0))
        # per-member serving rung + dominant tail cause (ISSUE 15
        # satellite): a degraded member is visible here BEFORE it
        # becomes a straggler verdict.  '-' for digest-less peers
        # (version skew), never a fake healthy 0.
        a = r.get("act") or {}
        prop.put(pre + "degrade_level",
                 a.get("lvl") if "lvl" in a else "-")
        prop.put(pre + "tail_cause",
                 escape_json(str(a.get("cause"))) if "cause" in a
                 else "-")
        prop.put(pre + "rtt_ms",
                 round(r["rtt_ms"], 1) if r["rtt_ms"] is not None else "-")
        for fam in fleetmod.DIGEST_FAMILIES:
            key = pre + fam.replace(".", "_") + "_"
            qs = r["quantiles"].get(fam)
            if qs is None:
                # absent family (version skew / no traffic): shown as
                # '-', NEVER as a fake zero percentile
                for lbl in ("p50", "p95", "p99"):
                    prop.put(key + lbl, "-")
            else:
                for lbl, v in zip(("p50", "p95", "p99"), qs):
                    prop.put(key + lbl, round(v, 2))

    # merged-vs-local comparison: the mesh-wide distribution any node
    # can compute from digests, next to this node's own windowed view
    fams = fleetmod.DIGEST_FAMILIES
    prop.put("families", len(fams))
    for i, fam in enumerate(fams):
        pre = f"families_{i}_"
        local = fl.local_counts(fam)
        merged = fl.merged_counts(fam)
        prop.put(pre + "name", escape_json(fam))
        prop.put(pre + "local_count", sum(local) if local else 0)
        prop.put(pre + "mesh_count", sum(merged))
        for lbl, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            prop.put(pre + "local_" + lbl, round(
                histogram.percentile_from_counts(local, q)
                if local else 0.0, 2))
            prop.put(pre + "mesh_" + lbl, round(
                histogram.percentile_from_counts(merged, q), 2))
        prop.put(pre + "local_spark", _sparkline(local or []))
        prop.put(pre + "mesh_spark", _sparkline(merged))

    now = time.time()
    frules = [(n, desc, st) for (n, desc, st) in
              (eng.rule_table() if eng is not None else [])
              if n.startswith("fleet_")]
    prop.put("rules", len(frules))
    for i, (name, desc, st) in enumerate(frules):
        pre = f"rules_{i}_"
        prop.put(pre + "name", escape_json(name))
        prop.put(pre + "description", escape_json(desc))
        prop.put(pre + "state", st.state)
        prop.put(pre + "cause", escape_json(st.cause))
        prop.put(pre + "since_s",
                 round(now - st.since, 1) if st.since else 0.0)
        prop.put(pre + "evidence", escape_json(" ".join(
            f"{k}={v}" for k, v in st.evidence.items())))
    return prop
