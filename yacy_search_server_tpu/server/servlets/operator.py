"""Operator surface — the admin-servlet breadth pass (VERDICT r2 #5).

~26 additional admin/UI servlets covering the most-used reference pages
(reference: htroot/ConfigAppearance_p.java, ConfigSearchPage_p.java,
ConfigRobotsTxt_p.java, AccessGrid_p.java, Connections_p.java,
ViewLog_p.java, Threaddump_p.java, Performance_p.java,
PerformanceSearch_p.java, CrawlCheck_p.java, RemoteCrawl_p.java,
Autocrawl_p.java, IndexSchema_p.java, IndexDeletion_p.java,
IndexImport*_p.java, Translator_p.java, ConfigHTCache_p.java,
RegexTest.java, BlacklistTest_p.java, SearchAccessRate_p.java,
yacyinteractive.java, robots.java, Help.java).

Every servlet fills a property map; pages with a bespoke template in
htroot/ render it, the rest render through the generic admin page
(env/generic_page.html) — real HTML chrome either way.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
import traceback

from ..objects import ServerObjects, escape_html
from . import servlet

# -- appearance / search page / portal -------------------------------------


@servlet("ConfigAppearance_p")
def config_appearance(header, post, sb):
    prop = ServerObjects()
    cfg = sb.config
    if post.get("set", ""):
        for key in ("promoteSearchPageGreeting", "locale.language",
                    "appearance.skin"):
            if post.get(key, "") != "":
                cfg.set(key, post.get(key))
    prop.put("greeting", escape_html(
        cfg.get("promoteSearchPageGreeting", "YaCy TPU P2P Web Search")))
    prop.put("language", escape_html(cfg.get("locale.language", "default")))
    prop.put("skin", escape_html(cfg.get("appearance.skin", "default")))
    return prop


_SEARCHPAGE_FLAGS = (
    "search.result.show.date", "search.result.show.size",
    "search.result.show.metadata", "search.result.show.proxy",
    "search.result.show.hostbrowser", "search.result.show.tags",
    "search.navigation.hosts", "search.navigation.filetype",
    "search.navigation.authors", "search.navigation.language",
)


@servlet("ConfigSearchPage_p")
def config_searchpage(header, post, sb):
    """Which elements the search result page renders (reference:
    ConfigSearchPage_p.java writes the same flag family)."""
    prop = ServerObjects()
    cfg = sb.config
    if post.get("set", ""):
        for key in _SEARCHPAGE_FLAGS:
            cfg.set(key, "true" if post.get_bool(key, False) else "false")
    prop.put("flags", len(_SEARCHPAGE_FLAGS))
    for i, key in enumerate(_SEARCHPAGE_FLAGS):
        prop.put(f"flags_{i}_name", key)
        prop.put(f"flags_{i}_value", 1 if cfg.get_bool(key, True) else 0)
        prop.put(f"flags_{i}_eol", 1 if i < len(_SEARCHPAGE_FLAGS) - 1 else 0)
    return prop


@servlet("ConfigRobotsTxt_p")
def config_robotstxt(header, post, sb):
    """What this NODE's own /robots.txt denies to visiting crawlers
    (reference: ConfigRobotsTxt_p.java -> RobotsTxtConfig)."""
    prop = ServerObjects()
    cfg = sb.config
    parts = ("all", "blog", "bookmarks", "network", "news", "status",
             "wiki", "dirs", "profile")
    if post.get("set", ""):
        for p in parts:
            cfg.set(f"httpd.robots.txt.{p}",
                    "true" if post.get_bool(p, False) else "false")
    prop.put("parts", len(parts))
    for i, p in enumerate(parts):
        prop.put(f"parts_{i}_name", p)
        prop.put(f"parts_{i}_value",
                 1 if cfg.get_bool(f"httpd.robots.txt.{p}", False) else 0)
        prop.put(f"parts_{i}_eol", 1 if i < len(parts) - 1 else 0)
    return prop


_ROBOTS_PART_PATHS = {
    "blog": "/Blog.html", "bookmarks": "/Bookmarks.html",
    "network": "/Network.html", "news": "/News.html",
    "status": "/Status.html", "wiki": "/Wiki.html",
    "dirs": "/htroot/", "profile": "/ViewProfile.html",
}


@servlet("robots")
def robots_txt(header, post, sb):
    """The node's own robots.txt (reference: htroot/robots.java)."""
    prop = ServerObjects()
    lines = ["User-agent: *"]
    cfg = sb.config
    if cfg.get_bool("httpd.robots.txt.all", False):
        lines.append("Disallow: /")
    else:
        for part, path in _ROBOTS_PART_PATHS.items():
            if cfg.get_bool(f"httpd.robots.txt.{part}", False):
                lines.append(f"Disallow: {path}")
    prop.raw_body = "\n".join(lines) + "\n"
    prop.raw_ctype = "text/plain; charset=utf-8"
    return prop


# -- access / connections ---------------------------------------------------


@servlet("AccessGrid_p")
def access_grid(header, post, sb):
    """Per-client access counts over the sliding window (reference:
    AccessGrid_p.java over serverAccessTracker)."""
    prop = ServerObjects()
    hosts = sb.access_tracker.access_hosts(600.0)[:200]
    prop.put("hosts", len(hosts))
    for i, (h, n) in enumerate(hosts):
        prop.put(f"hosts_{i}_host", escape_html(h))
        prop.put(f"hosts_{i}_count", n)
        prop.put(f"hosts_{i}_eol", 1 if i < len(hosts) - 1 else 0)
    prop.put("limit", sb.config.get_int("httpd.maxAccessPerHost.600s", 6000))
    return prop


@servlet("Connections_p")
def connections(header, post, sb):
    """Live server/loader activity (reference: Connections_p.java)."""
    prop = ServerObjects()
    threads = [t for t in threading.enumerate()]
    http_threads = [t for t in threads if "Thread-" in t.name
                    or "http" in t.name.lower()]
    prop.put("threadcount", len(threads))
    prop.put("httpthreads", len(http_threads))
    inflight = list(getattr(sb.loader, "_inflight", {}))[:50]
    prop.put("loading", len(inflight))
    for i, url in enumerate(inflight):
        prop.put(f"loading_{i}_url", escape_html(url))
        prop.put(f"loading_{i}_eol", 1 if i < len(inflight) - 1 else 0)
    return prop


@servlet("SearchAccessRate_p")
def search_access_rate(header, post, sb):
    """Abuse-throttle limits (reference: SearchAccessRate_p.java)."""
    prop = ServerObjects()
    cfg = sb.config
    if post.get("set", ""):
        for key in ("httpd.maxAccessPerHost.600s",):
            if post.get(key, ""):
                cfg.set(key, post.get(key))
    prop.put("maxAccessPerHost", cfg.get_int(
        "httpd.maxAccessPerHost.600s", 6000))
    prop.put("accesscalls", getattr(sb.access_tracker, "_access_calls", 0))
    return prop


# -- observability ----------------------------------------------------------


@servlet("ViewLog_p")
def view_log(header, post, sb):
    """Tail of the node log file (reference: ViewLog_p.java)."""
    prop = ServerObjects()
    n = min(post.get_int("lines", 100), 1000)
    lines: list[str] = []
    data_dir = getattr(sb, "data_dir", None)
    path = os.path.join(data_dir, "LOG", "yacy.log") if data_dir else None
    if path and os.path.exists(path):
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            f.seek(max(0, size - 256 * 1024))
            raw = f.read().decode("utf-8", "replace")
        lines = raw.splitlines()[-n:]
    from ...utils import logging as ylog
    prop.put("dropped", ylog.dropped_count())
    prop.put("lines", len(lines))
    for i, line in enumerate(lines):
        prop.put(f"lines_{i}_line", escape_html(line))
        prop.put(f"lines_{i}_eol", 1 if i < len(lines) - 1 else 0)
    return prop


@servlet("Threaddump_p")
def threaddump(header, post, sb):
    """Stack dump of every live thread (reference: Threaddump_p.java)."""
    prop = ServerObjects()
    frames = sys._current_frames()
    threads = sorted(threading.enumerate(), key=lambda t: t.name)
    prop.put("threads", len(threads))
    for i, t in enumerate(threads):
        p = f"threads_{i}_"
        prop.put(p + "name", escape_html(t.name))
        prop.put(p + "daemon", 1 if t.daemon else 0)
        frame = frames.get(t.ident)
        stack = "".join(traceback.format_stack(frame)) if frame else ""
        prop.put(p + "stack", escape_html(stack[-4000:]))
        prop.put(p + "eol", 1 if i < len(threads) - 1 else 0)
    return prop


@servlet("Performance_p")
def performance(header, post, sb):
    """Busy-thread overview (reference: Performance_p.java over the
    deployed BusyThreads; steer with Steering_p)."""
    prop = ServerObjects()
    names = sb.threads.names()
    prop.put("jobs", len(names))
    for i, name in enumerate(names):
        t = sb.threads.get(name)
        p = f"jobs_{i}_"
        prop.put(p + "name", escape_html(name))
        prop.put(p + "busy", getattr(t, "busy_cycles", 0))
        prop.put(p + "idle", getattr(t, "idle_cycles", 0))
        prop.put(p + "alive", 1 if t and t._thread
                 and t._thread.is_alive() else 0)
        prop.put(p + "eol", 1 if i < len(names) - 1 else 0)
    return prop


@servlet("PerformanceConcurrency_p")
def performance_concurrency(header, post, sb):
    """Indexing pipeline queue/worker metrics (reference:
    PerformanceConcurrency_p.java over WorkflowProcessor)."""
    prop = ServerObjects()
    procs = [getattr(sb, a, None) for a in
             ("_parse_proc", "_condense_proc", "_structure_proc",
              "_store_proc")]
    procs = [p for p in procs if p is not None]
    prop.put("processors", len(procs))
    for i, p in enumerate(procs):
        q = f"processors_{i}_"
        m = getattr(p, "metrics", None)
        prop.put(q + "name", escape_html(getattr(p, "name", f"stage{i}")))
        prop.put(q + "queued", p.queue_size())
        prop.put(q + "processed", getattr(m, "processed", 0) if m else 0)
        prop.put(q + "avgms", round(m.avg_exec_ms, 2) if m else 0)
        prop.put(q + "eol", 1 if i < len(procs) - 1 else 0)
    return prop


@servlet("PerformanceSearch_p")
def performance_search(header, post, sb):
    """Per-stage search timings (reference: PerformanceSearch_p.java over
    EventTracker SEARCH events)."""
    from ...utils.eventtracker import EClass, events
    prop = ServerObjects()
    evs = events(EClass.SEARCH)[-200:]
    by_stage: dict[str, list[float]] = {}
    for e in evs:
        by_stage.setdefault(e.label, []).append(e.duration_ms)
    stages = sorted(by_stage)
    prop.put("stages", len(stages))
    for i, s in enumerate(stages):
        durs = by_stage[s]
        p = f"stages_{i}_"
        prop.put(p + "name", escape_html(s))
        prop.put(p + "count", len(durs))
        prop.put(p + "avgms", round(sum(durs) / max(len(durs), 1), 2))
        prop.put(p + "maxms", round(max(durs), 2) if durs else 0)
        prop.put(p + "eol", 1 if i < len(stages) - 1 else 0)
    return prop


# -- crawl tools ------------------------------------------------------------


@servlet("CrawlCheck_p")
def crawl_check(header, post, sb):
    """Pre-crawl URL check: robots verdict + blacklist + cache state
    (reference: CrawlCheck_p.java)."""
    prop = ServerObjects()
    url = post.get("crawlingURL", post.get("url", "")).strip()
    prop.put("url", escape_html(url))
    prop.put("checked", 1 if url else 0)
    if url:
        try:
            allowed = sb.robots.is_allowed(url)
        except Exception:
            allowed = True
        prop.put("robotsallowed", 1 if allowed else 0)
        reason = sb.blacklist.crawler_reason(url)
        prop.put("blacklisted", 0 if reason is None else 1)
        prop.put("blacklistreason", escape_html(reason or ""))
        prop.put("cached", 1 if sb.htcache.has(url) else 0)
    return prop


@servlet("RemoteCrawl_p")
def remote_crawl(header, post, sb):
    """Remote-crawl participation settings (reference: RemoteCrawl_p.java)."""
    prop = ServerObjects()
    cfg = sb.config
    if post.get("set", ""):
        cfg.set("crawlResponse",
                "true" if post.get_bool("crawlResponse", False) else "false")
        if post.get("acceptCrawlLimit", ""):
            cfg.set("crawlResponse.ppm", post.get("acceptCrawlLimit"))
    prop.put("crawlResponse",
             1 if cfg.get_bool("crawlResponse", False) else 0)
    prop.put("ppm", cfg.get_int("crawlResponse.ppm", 60))
    return prop


@servlet("Autocrawl_p")
def autocrawl(header, post, sb):
    """Autocrawl configuration (reference: Autocrawl_p.java)."""
    prop = ServerObjects()
    cfg = sb.config
    if post.get("set", ""):
        cfg.set("autocrawl",
                "true" if post.get_bool("autocrawl", False) else "false")
        for key in ("autocrawl.rows", "autocrawl.days",
                    "autocrawl.deep.depth"):
            if post.get(key, ""):
                cfg.set(key, post.get(key))
    prop.put("autocrawl", 1 if cfg.get_bool("autocrawl", False) else 0)
    prop.put("rows", cfg.get_int("autocrawl.rows", 100))
    prop.put("days", cfg.get_int("autocrawl.days", 30))
    prop.put("depth", cfg.get_int("autocrawl.deep.depth", 3))
    return prop


# -- index tools ------------------------------------------------------------


@servlet("IndexSchema_p")
def index_schema(header, post, sb):
    """The live collection schema (reference: IndexSchema_p.java)."""
    from ...index.metadata import DOUBLE_FIELDS, INT_FIELDS, TEXT_FIELDS
    prop = ServerObjects()
    rows = [(f, "text") for f in TEXT_FIELDS] \
        + [(f, "int") for f in INT_FIELDS] \
        + [(f, "double") for f in DOUBLE_FIELDS]
    prop.put("fieldcount", len(rows))
    prop.put("fields", len(rows))
    for i, (name, kind) in enumerate(rows):
        prop.put(f"fields_{i}_name", name)
        prop.put(f"fields_{i}_type", kind)
        prop.put(f"fields_{i}_eol", 1 if i < len(rows) - 1 else 0)
    return prop


@servlet("IndexDeletion_p")
def index_deletion(header, post, sb):
    """Delete by URL or whole host (reference: IndexDeletion_p.java)."""
    from ...utils.hashes import url2hash
    prop = ServerObjects()
    deleted = 0
    url = post.get("urldelete", "").strip()
    host = post.get("hostdelete", "").strip().lower()
    if post.get("deleteIndex") and post.get("agree"):
        # the full wipe (reference IndexDeletion_p "delete the index"
        # with its are-you-sure gate; bin/clearindex.sh)
        meta = sb.index.metadata
        for d in range(meta.capacity()):
            if not meta.is_deleted(d) and sb.index.remove_document(
                    meta.urlhash_of(d)):
                deleted += 1
    if url:
        if sb.index.remove_document(url2hash(url)):
            deleted += 1
    if host:
        meta = sb.index.metadata
        suffix = "." + host
        docids = meta.facet_docids(
            "host_s", lambda h: h == host or h.endswith(suffix))
        for d in docids.tolist():
            if sb.index.remove_document(meta.urlhash_of(int(d))):
                deleted += 1
    prop.put("deleted", deleted)
    prop.put("doccount", sb.index.doc_count())
    return prop


@servlet("IndexImportWarc_p")
def import_warc(header, post, sb):
    """WARC dump import (reference: IndexImportWarc_p.java). The file
    must already be on the node (surrogates dir or an absolute path
    under DATA)."""
    prop = ServerObjects()
    path = post.get("file", "").strip()
    prop.put("imported", 0)
    prop.put("error", "")
    if path:
        resolved = _surrogate_path(sb, path)
        if resolved is None:
            prop.put("error", "file must live under DATA")
        else:
            try:
                from ...document.importer import WarcImporter
                imported = [0]

                def sink(doc):
                    sb.index.store_document(doc, collection="import")
                    imported[0] += 1
                WarcImporter(sink).import_file(resolved)
                prop.put("imported", imported[0])
            except Exception as e:
                prop.put("error", escape_html(str(e)))
    return prop


def _surrogate_path(sb, path: str) -> str | None:
    """Imports only read files inside the node's own DATA dir."""
    data_dir = getattr(sb, "data_dir", None)
    if not data_dir:
        return path if os.path.exists(path) else None
    resolved = os.path.realpath(os.path.join(data_dir, path))
    root = os.path.realpath(data_dir)
    return resolved if resolved.startswith(root + os.sep) else None


@servlet("IndexImportOAIPMH_p")
def import_oaipmh(header, post, sb):
    """OAI-PMH harvest trigger (reference: IndexImportOAIPMH_p.java)."""
    prop = ServerObjects()
    endpoint = post.get("urlstartone", post.get("url", "")).strip()
    prop.put("imported", 0)
    prop.put("error", "")
    if endpoint:
        try:
            from ...crawler.request import Request
            from ...document.importer.oaipmh import OAIPMHHarvester
            imported = [0]

            def sink(doc):
                sb.index.store_document(doc, collection="oaipmh")
                imported[0] += 1

            def fetcher(u):
                resp = sb.loader.load(Request(url=u))
                return resp.content if resp.status == 200 else b""
            OAIPMHHarvester(endpoint, fetcher, sink).harvest()
            prop.put("imported", imported[0])
        except Exception as e:
            prop.put("error", escape_html(str(e)))
    return prop


@servlet("IndexImportMediawiki_p")
def import_mediawiki(header, post, sb):
    """MediaWiki XML dump import (reference: IndexImportMediawiki_p.java)."""
    prop = ServerObjects()
    path = post.get("file", "").strip()
    prop.put("imported", 0)
    prop.put("error", "")
    if path:
        resolved = _surrogate_path(sb, path)
        if resolved is None:
            prop.put("error", "file must live under DATA")
        else:
            try:
                from ...document.importer import MediawikiImporter
                imported = [0]

                def sink(doc):
                    sb.index.store_document(doc, collection="import")
                    imported[0] += 1
                MediawikiImporter(sink).import_file(resolved)
                prop.put("imported", imported[0])
            except Exception as e:
                prop.put("error", escape_html(str(e)))
    return prop


# -- misc tools -------------------------------------------------------------


@servlet("Translator_p")
def translator(header, post, sb):
    """Loaded UI translation table (reference: Translator_p.java)."""
    from ..translation import load_locale
    prop = ServerObjects()
    lang = post.get("lang", sb.config.get("locale.language", "default"))
    locales = os.path.join(sb.data_dir, "LOCALES") \
        if getattr(sb, "data_dir", None) else None
    table = load_locale(locales, lang)
    entries = sorted({(src, dst)
                      for pairs in table._sections.values()
                      for src, dst in pairs})[:500]
    prop.put("lang", escape_html(lang))
    prop.put("entries", len(entries))
    for i, (src, dst) in enumerate(entries):
        prop.put(f"entries_{i}_source", escape_html(src))
        prop.put(f"entries_{i}_target", escape_html(dst))
        prop.put(f"entries_{i}_eol", 1 if i < len(entries) - 1 else 0)
    return prop


_HTCACHE_STATS: dict = {}


@servlet("ConfigHTCache_p")
def config_htcache(header, post, sb):
    """Page-cache settings + stats (reference: ConfigHTCache_p.java)."""
    prop = ServerObjects()
    cfg = sb.config
    if post.get("set", "") and post.get("maxCacheSize", ""):
        cfg.set("proxyCacheSize", post.get("maxCacheSize"))
    if post.get("clear"):
        prop.put("cleared", sb.htcache.clear())
        _HTCACHE_STATS.pop(getattr(sb.htcache, "data_dir", None), None)
    data_dir = getattr(sb.htcache, "data_dir", None)
    # the full-walk stat is expensive on big caches: cache it briefly
    cached = _HTCACHE_STATS.get(data_dir)
    if cached and time.time() - cached[0] < 30.0:
        files, size = cached[1], cached[2]
    else:
        files = size = 0
        if data_dir and os.path.isdir(data_dir):
            for root, _dirs, names in os.walk(data_dir):
                for n in names:
                    files += 1
                    try:
                        size += os.path.getsize(os.path.join(root, n))
                    except OSError:
                        pass
        _HTCACHE_STATS[data_dir] = (time.time(), files, size)
    prop.put("entries", files)
    prop.put("sizemb", round(size / (1 << 20), 2))
    prop.put("maxsize", cfg.get_int("proxyCacheSize", 4096))
    return prop


@servlet("RegexTest")
def regex_test(header, post, sb):
    """must-match/must-not-match pattern tester (reference: RegexTest.java).

    Admin-gated by default (security.DEFAULT_ADMIN_PATHS — CPython's
    backtracking engine has no timeout); input caps stay as defense in
    depth for operators who re-open the mount."""
    prop = ServerObjects()
    text = post.get("text", "")[:4096]
    pattern = post.get("regex", "")[:1024]
    prop.put("text", escape_html(text))
    prop.put("regex", escape_html(pattern))
    matched = error = ""
    if pattern:
        try:
            matched = "1" if re.fullmatch(pattern, text) else "0"
        except re.error as e:
            error = str(e)
    prop.put("matches", matched)
    prop.put("error", escape_html(error))
    return prop


@servlet("BlacklistTest_p")
def blacklist_test(header, post, sb):
    """Test one URL against the active blacklists (reference:
    BlacklistTest_p.java)."""
    prop = ServerObjects()
    url = post.get("testurl", post.get("url", "")).strip()
    prop.put("url", escape_html(url))
    prop.put("tested", 1 if url else 0)
    if url:
        reason = sb.blacklist.crawler_reason(url)
        prop.put("listed", 0 if reason is None else 1)
        prop.put("reason", escape_html(reason or ""))
        types = [t for t in ("crawler", "dht", "search", "surftips",
                             "news", "proxy")
                 if sb.blacklist.is_listed(t, url)]
        prop.put("types", escape_html(",".join(types)))
    return prop


@servlet("Help")
def help_page(header, post, sb):
    prop = ServerObjects()
    prop.put("version", escape_html(
        sb.config.get("version", "")))
    return prop


@servlet("yacyinteractive")
def yacy_interactive(header, post, sb):
    """The JS live-search page (reference: yacyinteractive.java — the
    template drives /suggest + /yacysearch.json from the browser)."""
    prop = ServerObjects()
    prop.put("promoteSearchPageGreeting", escape_html(
        sb.config.get("promoteSearchPageGreeting",
                      "YaCy TPU P2P Web Search")))
    prop.put("former", escape_html(post.get("query", "")))
    return prop


@servlet("DeviceStore_p")
def device_store(header, post, sb):
    """The serving-store dashboard: arena occupancy, prune/batch/join
    coverage, mesh layout (observability for the device path — the
    reference's PerformanceMemory table-tracker idea applied to the
    TPU arena)."""
    prop = ServerObjects()
    ds = sb.index.devstore
    if ds is None:
        prop.put("enabled", 0)
        prop.put("kind", "none")
        prop.put("rows", 0)
        return prop
    prop.put("enabled", 1)
    kind = type(ds).__name__
    prop.put("kind", kind)
    rows: list[tuple[str, object]] = [
        ("queries_served", getattr(ds, "queries_served", 0)),
        ("fallbacks", getattr(ds, "fallbacks", 0)),
        ("join_served", getattr(ds, "join_served", 0)),
        ("join_fallbacks", getattr(ds, "join_fallbacks", 0)),
    ]
    if kind == "DeviceSegmentStore":
        c = ds.counters()
        rows += [
            ("arena_rows_used", ds.arena.used_rows),
            ("arena_rows_capacity", ds.arena.capacity_rows),
            ("arena_bytes", ds.arena.bytes_used()),
            ("live_rows", ds.live_rows()),
            ("prune_rounds", ds.prune_rounds),
            ("pruned_tiles", ds.pruned_tiles),
            ("batching", 1 if ds._batcher is not None else 0),
            # versioned top-k result cache + round-trip accounting
            ("rank_cache_hits", c["rank_cache_hits"]),
            ("rank_cache_stale", c["rank_cache_stale"]),
            ("arena_epoch", c["arena_epoch"]),
            ("device_round_trips", c["device_round_trips"]),
            ("rt_per_query",
             round(c["device_round_trips"]
                   / max(c["queries_served"], 1), 3)),
            # silicon accounting (Performance_Roofline_p has the full
            # per-kernel table; these are the per-query headline fields)
            ("util_pct_p50", c["util_pct_p50"]),
            ("util_pct_p95", c["util_pct_p95"]),
            ("bound", c["bound"]),
            # compressed residency + tier ladder (ISSUE 8): per-tier
            # occupancy, hit attribution and the promotion flow
            ("packed_residency", 1 if ds.packed_residency else 0),
            ("compression_ratio", c["packed_compression_ratio"]),
            ("tier_hot_bytes", c["tier_hot_bytes"]),
            ("tier_warm_bytes", c["tier_warm_bytes"]),
            ("tier_cold_bytes", c["tier_cold_bytes"]),
            ("tier_hits_hot_warm_cold",
             f"{c['tier_hot_hits']}/{c['tier_warm_hits']}"
             f"/{c['tier_cold_hits']}"),
            ("tier_promotions_warm_hot", c["tier_promotions_warm_hot"]),
            ("tier_promotions_cold_hot", c["tier_promotions_cold_hot"]),
            ("tier_demotions_hot_warm", c["tier_demotions_hot_warm"]),
            ("term_cache_hits", c["term_cache_hits"]),
            ("term_cache_evictions", c["term_cache_evictions"]),
            # dense-first ANN (ISSUE 11): candidate-generation coverage
            # + the vector side of the residency ledger — with
            # dense_fwd_bytes, every resident byte is on this dashboard
            ("ann_vectors", c["ann_vectors"]),
            ("ann_clusters", c["ann_clusters"]),
            ("ann_queries", c["ann_queries"]),
            ("ann_dispatches", c["ann_dispatches"]),
            ("ann_host_queries", c["ann_host_queries"]),
            ("ann_bytes_hot_warm_cold",
             f"{c['ann_hot_bytes']}/{c['ann_warm_bytes']}"
             f"/{c['ann_cold_bytes']}"),
            ("ann_hits_hot_warm_cold",
             f"{c['ann_tier_hot_hits']}/{c['ann_tier_warm_hits']}"
             f"/{c['ann_tier_cold_hits']}"),
            ("ann_promotions", c["ann_promotions"]),
            ("dense_fwd_bytes", c["dense_fwd_bytes"]),
        ]
    elif kind == "MeshSegmentStore":
        rows += [
            ("mesh_term_axis", ds.n_term),
            ("mesh_doc_axis", ds.n_doc),
            ("mesh_cells", ds.n_cells),
            ("live_rows", ds.live_rows()),
            ("cell_rows_max", max((c.used for c in ds._cells),
                                  default=0)),
        ]
    prop.put("rows", len(rows))
    for i, (name, v) in enumerate(rows):
        prop.put(f"rows_{i}_key", name)
        prop.put(f"rows_{i}_value", v)
    return prop
