"""Performance_Ingest_p — the write path's operator panel (ISSUE 13).

The read path has DeviceStore_p / Performance_Health_p; until now the
write path (crawl → parse → RWI flush → device pack → merge) had no
surface at all — flush and merge timing were invisible side effects of
buffer thresholds.  This panel renders the crawl-to-searchable SLO per
tier (windowed p50/p95/p99 + sparkline for ``ingest.searchable`` /
``.flushed`` / ``.device`` and the ``.backpressure`` wall), the ingest
tracker's doc counters, the merge/promotion scheduler's deferral state
(with the parked-promotion count and the pending merge ask), the
``ingest_slo_searchable`` rule verdict, and the ``merge_scheduler``
actuator's recent breadcrumbs — the whole defend-the-SLO loop on one
page, next to the freshness it protects."""

from __future__ import annotations

import time

from ...ingest import slo as ingest_slo
from ...utils import histogram
from ..objects import ServerObjects, escape_json
from . import servlet
from .health import _sparkline

# panel order: the SLO tiers first, then the wall that explains them
_FAMILIES = ("ingest.searchable", "ingest.flushed", "ingest.device",
             "ingest.backpressure")


@servlet("Performance_Ingest_p")
def respond_ingest(header: dict, post: ServerObjects,
                   sb) -> ServerObjects:
    prop = ServerObjects()
    eng = getattr(sb, "health", None)
    if post.get("tick", "") == "1" and eng is not None:
        eng.tick()

    prop.put("families", len(_FAMILIES))
    for i, fam in enumerate(_FAMILIES):
        pre = f"families_{i}_"
        h = histogram.get(fam)
        counts = h.windowed_counts() if h is not None else []
        prop.put(pre + "name", escape_json(fam))
        prop.put(pre + "help", escape_json(h.help if h else ""))
        prop.put(pre + "window_count", sum(counts))
        prop.put(pre + "total_count", h.count if h is not None else 0)
        for lbl, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            prop.put(pre + lbl + "_ms", round(
                histogram.percentile_from_counts(counts, q)
                if counts else 0.0, 3))
        prop.put(pre + "spark", _sparkline(counts))

    for k, v in ingest_slo.TRACKER.counters().items():
        prop.put(f"tracker_{k}", v)

    sched = getattr(sb, "ingest_scheduler", None)
    prop.put("scheduler", 1 if sched is not None else 0)
    if sched is not None:
        for k, v in sched.counters().items():
            prop.put(f"scheduler_{k}", v)
        pend = sched.pending_merge()
        prop.put("scheduler_pending_max_runs",
                 pend if pend is not None else "-")
        prop.put("scheduler_defer_age_s",
                 round(time.monotonic() - sched.defer_since, 1)
                 if sched.deferred and sched.defer_since else 0.0)

    ds = getattr(sb.index, "devstore", None)
    prop.put("device_builds",
             getattr(ds, "ingest_device_builds", 0) if ds else 0)
    prop.put("device_build_enabled",
             1 if getattr(ds, "ingest_device_build", False) else 0)

    # the freshness verdict + the actuator's trail, same rendering as
    # Performance_Health_p so operators read one idiom everywhere
    now = time.time()
    st = eng.states.get("ingest_slo_searchable") if eng is not None \
        else None
    prop.put("rule_state", st.state if st is not None else "ok")
    prop.put("rule_cause", escape_json(st.cause) if st is not None
             else "")
    prop.put("rule_since_s",
             round(now - st.since, 1) if st is not None and st.since
             else 0.0)
    prop.put("rule_evidence", escape_json(" ".join(
        f"{k}={v}" for k, v in st.evidence.items()))
        if st is not None else "")

    act = getattr(sb, "actuators", None)
    crumbs = [c for c in (act.recent_breadcrumbs(64) if act else [])
              if c.get("actuator") == "merge_scheduler"][-16:]
    prop.put("breadcrumbs", len(crumbs))
    for i, c in enumerate(reversed(crumbs)):
        pre = f"breadcrumbs_{i}_"
        prop.put(pre + "time", int(c.get("ts", 0)))
        prop.put(pre + "dir", escape_json(c.get("dir", "")))
        prop.put(pre + "cause", escape_json(c.get("cause", "")))
    return prop
