"""Performance_Tail_p — the tail-forensics operator panel (ISSUE 15).

Performance_Trace_p shows WHERE a slow query spent its wall;
Performance_Health_p shows THAT the SLO is burning.  This panel shows
WHY: the verdict ring (every over-threshold serving query with its one
classified cause), the windowed cause histogram, the cross-process
straggler scoreboard (which mesh member was the slowest leg, how often,
by how much), the newest assembled mesh waterfall, and the dispatch-
wave log (queue depth / occupancy / compile-vs-reuse / tier state per
wave).  ``format=json`` exports the whole view for ``tools/
tail_report.py`` and offline analysis."""

from __future__ import annotations

import json

from ...utils import tailattr
from ..objects import ServerObjects, escape_json
from . import servlet


def tail_view(sb) -> dict:
    """The full forensics view as one JSON-serializable dict (shared by
    the servlet's format=json export and the bench artifact)."""
    # finalize any owed mesh verdicts whose segments never fully
    # arrived (lull after a burst): the operator asking is exactly
    # when a pending verdict must stop waiting
    tailattr.MESH.flush_pending()
    ctr = tailattr.ATTR.counters()
    mesh = getattr(sb, "mesh_member", None)
    return {
        "enabled": tailattr.enabled(),
        "min_ms": tailattr.MIN_MS,
        "classified_total": ctr["classified_total"],
        "cause_totals": ctr["causes"],
        "causes_windowed": tailattr.windowed_causes(),
        "top_cause": tailattr.top_cause(),
        "stragglers": ctr["stragglers"],
        "verdicts": [v.to_json() for v in tailattr.verdicts(50)],
        "scoreboard": tailattr.scoreboard(),
        "waterfall": tailattr.MESH.waterfall(),
        "segments_merged": tailattr.MESH.segments_merged,
        "pending_partial": tailattr.MESH.pending_partial,
        "waves": tailattr.ATTR.wave_log(30),
        "mesh_member": mesh.process_id if mesh is not None else None,
    }


@servlet("Performance_Tail_p")
def respond_tail(header: dict, post: ServerObjects, sb) -> ServerObjects:
    view = tail_view(sb)
    if post.get("format", "") == "json":
        prop = ServerObjects()
        prop.raw_body = json.dumps(view, indent=1)
        prop.raw_ctype = "application/json; charset=utf-8"
        return prop
    prop = ServerObjects()
    prop.put("enabled", 1 if view["enabled"] else 0)
    prop.put("min_ms", view["min_ms"])
    prop.put("classified_total", view["classified_total"])
    prop.put("top_cause", escape_json(view["top_cause"]))
    prop.put("segments_merged", view["segments_merged"])

    causes = [(c, view["causes_windowed"].get(c, 0),
               view["cause_totals"].get(c, 0)) for c in tailattr.CAUSES]
    prop.put("causes", len(causes))
    for i, (cause, win, tot) in enumerate(causes):
        pre = f"causes_{i}_"
        prop.put(pre + "cause", escape_json(cause))
        prop.put(pre + "windowed", win)
        prop.put(pre + "total", tot)

    verdicts = view["verdicts"]
    prop.put("verdicts", len(verdicts))
    for i, v in enumerate(verdicts):
        pre = f"verdicts_{i}_"
        prop.put(pre + "ts", v["ts"])
        prop.put(pre + "trace_id", escape_json(v["trace_id"]))
        prop.put(pre + "root", escape_json(v["root"]))
        prop.put(pre + "dur_ms", v["dur_ms"])
        prop.put(pre + "cause", escape_json(v["cause"]))
        prop.put(pre + "member", escape_json(v.get("member", "")))
        prop.put(pre + "evidence", escape_json(
            " ".join(f"{k}={v2}" for k, v2 in v["evidence"].items())))

    board = view["scoreboard"]
    prop.put("scoreboard", len(board))
    for i, row in enumerate(board):
        pre = f"scoreboard_{i}_"
        for key in ("member", "steps", "slowest_count", "slowest_frac",
                    "mean_margin_ms", "max_margin_ms", "mean_exec_ms"):
            v = row[key]
            prop.put(pre + key, escape_json(v) if isinstance(v, str)
                     else v)

    wf = view["waterfall"]
    prop.put("waterfall", 1 if wf else 0)
    if wf:
        prop.put("waterfall_seq", wf["seq"])
        prop.put("waterfall_trace", escape_json(wf["trace_id"]))
        prop.put("waterfall_mode", escape_json(wf["mode"]))
        prop.put("waterfall_dur_ms", wf["dur_ms"])
        prop.put("waterfall_members", len(wf["members"]))
        for i, m in enumerate(wf["members"]):
            pre = f"waterfall_members_{i}_"
            prop.put(pre + "member", m["m"])
            prop.put(pre + "q_ms", m["q_ms"])
            prop.put(pre + "commit_ms", m["commit_ms"])
            # entry_ms IS the straggler signal (the slowed member's
            # lateness lands here while the innocents' exec inflates
            # blocking at collective entry) — the panel must show it
            prop.put(pre + "entry_ms", m.get("entry_ms", 0.0))
            prop.put(pre + "exec_ms", m["exec_ms"])
            prop.put(pre + "mode", escape_json(m["mode"]))

    waves = view["waves"]
    prop.put("waves", len(waves))
    for i, w in enumerate(waves):
        pre = f"waves_{i}_"
        prop.put(pre + "kernel", escape_json(w.get("kernel", "?")))
        prop.put(pre + "n", w.get("n", 0))
        prop.put(pre + "occ", w.get("occ", 0.0))
        prop.put(pre + "qdepth", w.get("qdepth", 0))
        prop.put(pre + "issue_ms", w.get("issue_ms", 0.0))
        prop.put(pre + "compile", 1 if w.get("compile") else 0)
        prop.put(pre + "merge_deferred",
                 1 if w.get("merge_deferred") else 0)
        prop.put(pre + "cold_hits", w.get("tier_cold_hits", 0))
    return prop
