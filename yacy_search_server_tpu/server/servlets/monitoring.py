"""Monitoring + inspection servlets: memory dashboard, crawl results,
cached-page viewer, profiling graph.

Capability equivalents of the reference's operations pages (reference:
htroot/PerformanceMemory_p.java — heap/tables memory dashboard backed by
MemoryControl; htroot/CrawlResults.java — per-origin crawl outcome lists
incl. the error cache; htroot/ViewFile.java — render a cached page's
text/metadata from the HTCache; htroot/PerformanceGraph.java — the
EventTracker time-series rendered as a PNG via ProfilingGraph)."""

from __future__ import annotations

from ...utils.eventtracker import EClass, events
from ...utils.memory import MemoryControl
from ..objects import ServerObjects, escape_json
from . import servlet


@servlet("PerformanceMemory_p")
def respond_memory(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("used_bytes", MemoryControl.used())
    prop.put("available_bytes", MemoryControl.available())
    prop.put("short_status", 1 if MemoryControl.short_status() else 0)
    # per-store accounting (the reference's table/heap trackers)
    rows = [
        ("rwi.ram_postings", sb.index.rwi.ram_postings_count),
        ("rwi.total_postings", sb.index.rwi.total_postings()),
        ("rwi.runs", sb.index.rwi.run_count()),
        ("metadata.docs", len(sb.index.metadata)),
        ("search.cached_events", len(sb.search_cache)),
        ("frontier.local", _frontier_size(sb)),
        ("tables", len(sb.tables.tables())),
    ]
    prop.put("stores", len(rows))
    for i, (name, v) in enumerate(rows):
        prop.put(f"stores_{i}_name", name)
        prop.put(f"stores_{i}_value", int(v))
    return prop


def _frontier_size(sb) -> int:
    from ...crawler.frontier import StackType
    return sb.noticed.size(StackType.LOCAL)


@servlet("CrawlResults")
def respond_crawl_results(header: dict, post: ServerObjects,
                          sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("indexed_count", sb.indexed_count)
    errors = sb.crawl_queues.error_cache.recent(post.get_int("count", 50))
    prop.put("errors", len(errors))
    for i, (url, reason, ts) in enumerate(errors):
        prop.put(f"errors_{i}_url", escape_json(url))
        prop.put(f"errors_{i}_reason", escape_json(reason))
        prop.put(f"errors_{i}_time", int(ts))
    return prop


@servlet("ViewFile")
def respond_viewfile(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Inspect a document as the index sees it: cached raw content,
    extracted text, or metadata row (ViewFile.java viewMode semantics)."""
    prop = ServerObjects()
    url = post.get("url", "")
    mode = post.get("viewMode", "parsed")
    if not url:
        prop.put("info", "missing url")
        return prop
    from ...utils.hashes import url2hash
    docid = sb.index.metadata.docid(url2hash(url))
    if mode == "raw":
        got = sb.htcache.get(url)
        if got is None:
            prop.put("info", "not in cache")
            return prop
        content, headers = got
        prop.raw_body = content
        prop.raw_ctype = headers.get("content-type",
                                     "application/octet-stream")
        return prop
    if docid is None:
        prop.put("info", "not indexed")
        return prop
    m = sb.index.metadata.get(docid)
    prop.put("url", escape_json(url))
    prop.put("title", escape_json(m.get("title", "")))
    prop.put("docid", docid)
    if mode == "metadata":
        for k, v in sorted(m.fields.items()):
            if k != "text_t":
                prop.put(f"field_{k}", escape_json(str(v)))
    else:   # parsed text
        prop.put("text", escape_json(m.get("text_t", "")[:20000]))
        prop.put("wordcount", m.get("wordcount_i", 0))
    return prop


@servlet("Performance_Roofline_p")
def respond_roofline(header: dict, post: ServerObjects,
                     sb) -> ServerObjects:
    """Silicon accounting dashboard (ISSUE 1): every serving kernel's
    achieved FLOP/s / GB/s placed against the device roofline, plus the
    per-query utilization percentiles the rank-service counters carry.
    `format=png` renders the log-log roofline chart via the raster
    layer; the default response is the numeric table (template/API
    form, like DeviceStore_p)."""
    from ...ops import roofline as RF
    from ...utils.profiler import PROFILER

    peak = PROFILER.peak
    points = PROFILER.snapshot()
    if post.get("format", "") == "png":
        prop = ServerObjects()
        prop.raw_body = _roofline_png(points, peak)
        prop.raw_ctype = "image/png"
        return prop
    prop = ServerObjects()
    prop.put("device", escape_json(peak.name))
    prop.put("peak_tflops", round(peak.flops_per_s / 1e12, 3))
    prop.put("peak_gbps", round(peak.bytes_per_s / 1e9, 1))
    prop.put("ridge_flops_per_byte", round(peak.ridge, 2))
    util = PROFILER.query_util()
    prop.put("util_pct_p50", util["util_pct_p50"])
    prop.put("util_pct_p95", util["util_pct_p95"])
    prop.put("bound", util["bound"])
    prop.put("kernels", len(points))
    for i, p in enumerate(points):
        prop.put(f"kernels_{i}_name", p.kernel)
        prop.put(f"kernels_{i}_gflops", round(p.flops / 1e9, 3))
        prop.put(f"kernels_{i}_mbytes", round(p.bytes / 1e6, 2))
        prop.put(f"kernels_{i}_intensity", round(p.intensity, 2))
        prop.put(f"kernels_{i}_achieved_gflops_s",
                 round(p.achieved_flops_per_s / 1e9, 3))
        prop.put(f"kernels_{i}_achieved_gbytes_s",
                 round(p.achieved_bytes_per_s / 1e9, 3))
        prop.put(f"kernels_{i}_bound", p.bound)
        prop.put(f"kernels_{i}_util_pct", p.util_pct)
    return prop


def _roofline_png(points, peak, w: int = 640, h: int = 360) -> bytes:
    """Log-log roofline: the memory-bandwidth diagonal and the compute
    ceiling, with one dot per profiled kernel at (intensity, achieved
    FLOP/s)."""
    import math

    from ...visualization.raster import RasterPlotter
    img = RasterPlotter(w, h, background=(10, 10, 30))
    x0, y0, x1, y1 = 56, 24, w - 16, h - 44
    lx_min, lx_max = -2.0, 4.0                 # intensity 0.01..10^4 f/B
    ly_max = math.log10(max(peak.flops_per_s, 1.0))
    ly_min = ly_max - 8.0                      # 8 decades of FLOP/s

    def px(v):
        lv = min(max(math.log10(max(v, 1e-9)), lx_min), lx_max)
        return int(x0 + (lv - lx_min) / (lx_max - lx_min) * (x1 - x0))

    def py(v):
        lv = min(max(math.log10(max(v, 1.0)), ly_min), ly_max)
        return int(y1 - (lv - ly_min) / (ly_max - ly_min) * (y1 - y0))

    img.rect(x0, y0, x1, y1, (60, 60, 90))
    # the two roofs meet at the ridge point
    ridge = peak.ridge
    img.line(px(10 ** lx_min), py(10 ** lx_min * peak.bytes_per_s),
             px(ridge), py(peak.flops_per_s), (230, 180, 60))
    img.line(px(ridge), py(peak.flops_per_s),
             px(10 ** lx_max), py(peak.flops_per_s), (230, 180, 60))
    img.text(x0 + 4, y0 + 4,
             f"{peak.name}  {peak.flops_per_s / 1e12:.0f} TF/S  "
             f"{peak.bytes_per_s / 1e9:.0f} GB/S", (200, 200, 220))
    for i, p in enumerate(points):
        x, y = px(p.intensity), py(p.achieved_flops_per_s)
        color = (120, 200, 255) if p.bound == "memory" else (255, 140, 160)
        img.dot(x, y, color, radius=3)
        img.text(min(x + 6, w - 120), max(y - 4, y0 + 2),
                 f"{p.kernel[:16].upper()} {p.util_pct:.1f}", color)
    img.text(x0, h - 32, "X: FLOPS/BYTE   Y: FLOP/S   "
             "BLUE: MEMORY-BOUND  RED: COMPUTE-BOUND", (160, 160, 180))
    return img.png_bytes()


@servlet("PerformanceGraph")
def respond_perfgraph(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """EventTracker time-series as a PNG bar graph (ProfilingGraph)."""
    from ...visualization.raster import RasterPlotter
    try:
        ecl = EClass[post.get("set", "SEARCH").upper()]
    except KeyError:
        ecl = EClass.SEARCH
    evs = events(ecl)[-60:]
    w, h = 640, 240
    img = RasterPlotter(w, h, background=(10, 10, 30))
    img.text(8, 6, f"{ecl.name} EVENTS: {len(evs)}", (200, 200, 220))
    if evs:
        maxd = max(max(e.duration_ms for e in evs), 1.0)
        bw = max(2, (w - 20) // max(len(evs), 1))
        for i, e in enumerate(evs):
            bh = int((e.duration_ms / maxd) * (h - 60))
            x = 10 + i * bw
            img.rect(x, h - 20 - bh, x + bw - 2, h - 20,
                     (90, 200, 140), fill=True)
        img.text(8, h - 12, f"MAX {maxd:.1f} MS", (160, 160, 180))
    prop = ServerObjects()
    prop.raw_body = img.png_bytes()
    prop.raw_ctype = "image/png"
    return prop
