"""Monitoring + inspection servlets: memory dashboard, crawl results,
cached-page viewer, profiling graph.

Capability equivalents of the reference's operations pages (reference:
htroot/PerformanceMemory_p.java — heap/tables memory dashboard backed by
MemoryControl; htroot/CrawlResults.java — per-origin crawl outcome lists
incl. the error cache; htroot/ViewFile.java — render a cached page's
text/metadata from the HTCache; htroot/PerformanceGraph.java — the
EventTracker time-series rendered as a PNG via ProfilingGraph)."""

from __future__ import annotations

from ...utils.eventtracker import EClass, events
from ...utils.memory import MemoryControl
from ..objects import ServerObjects, escape_json
from . import servlet


@servlet("PerformanceMemory_p")
def respond_memory(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("used_bytes", MemoryControl.used())
    prop.put("available_bytes", MemoryControl.available())
    prop.put("short_status", 1 if MemoryControl.short_status() else 0)
    # per-store accounting (the reference's table/heap trackers)
    rows = [
        ("rwi.ram_postings", sb.index.rwi.ram_postings_count),
        ("rwi.total_postings", sb.index.rwi.total_postings()),
        ("rwi.runs", sb.index.rwi.run_count()),
        ("metadata.docs", len(sb.index.metadata)),
        ("search.cached_events", len(sb.search_cache)),
        ("frontier.local", _frontier_size(sb)),
        ("tables", len(sb.tables.tables())),
    ]
    prop.put("stores", len(rows))
    for i, (name, v) in enumerate(rows):
        prop.put(f"stores_{i}_name", name)
        prop.put(f"stores_{i}_value", int(v))
    return prop


def _frontier_size(sb) -> int:
    from ...crawler.frontier import StackType
    return sb.noticed.size(StackType.LOCAL)


@servlet("CrawlResults")
def respond_crawl_results(header: dict, post: ServerObjects,
                          sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("indexed_count", sb.indexed_count)
    errors = sb.crawl_queues.error_cache.recent(post.get_int("count", 50))
    prop.put("errors", len(errors))
    for i, (url, reason, ts) in enumerate(errors):
        prop.put(f"errors_{i}_url", escape_json(url))
        prop.put(f"errors_{i}_reason", escape_json(reason))
        prop.put(f"errors_{i}_time", int(ts))
    return prop


@servlet("ViewFile")
def respond_viewfile(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Inspect a document as the index sees it: cached raw content,
    extracted text, or metadata row (ViewFile.java viewMode semantics)."""
    prop = ServerObjects()
    url = post.get("url", "")
    mode = post.get("viewMode", "parsed")
    if not url:
        prop.put("info", "missing url")
        return prop
    from ...utils.hashes import url2hash
    docid = sb.index.metadata.docid(url2hash(url))
    if mode == "raw":
        got = sb.htcache.get(url)
        if got is None:
            prop.put("info", "not in cache")
            return prop
        content, headers = got
        prop.raw_body = content
        prop.raw_ctype = headers.get("content-type",
                                     "application/octet-stream")
        return prop
    if docid is None:
        prop.put("info", "not indexed")
        return prop
    m = sb.index.metadata.get(docid)
    prop.put("url", escape_json(url))
    prop.put("title", escape_json(m.get("title", "")))
    prop.put("docid", docid)
    if mode == "metadata":
        for k, v in sorted(m.fields.items()):
            if k != "text_t":
                prop.put(f"field_{k}", escape_json(str(v)))
    else:   # parsed text
        prop.put("text", escape_json(m.get("text_t", "")[:20000]))
        prop.put("wordcount", m.get("wordcount_i", 0))
    return prop


@servlet("PerformanceGraph")
def respond_perfgraph(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """EventTracker time-series as a PNG bar graph (ProfilingGraph)."""
    from ...visualization.raster import RasterPlotter
    try:
        ecl = EClass[post.get("set", "SEARCH").upper()]
    except KeyError:
        ecl = EClass.SEARCH
    evs = events(ecl)[-60:]
    w, h = 640, 240
    img = RasterPlotter(w, h, background=(10, 10, 30))
    img.text(8, 6, f"{ecl.name} EVENTS: {len(evs)}", (200, 200, 220))
    if evs:
        maxd = max(max(e.duration_ms for e in evs), 1.0)
        bw = max(2, (w - 20) // max(len(evs), 1))
        for i, e in enumerate(evs):
            bh = int((e.duration_ms / maxd) * (h - 60))
            x = 10 + i * bw
            img.rect(x, h - 20 - bh, x + bw - 2, h - 20,
                     (90, 200, 140), fill=True)
        img.text(8, h - 12, f"MAX {maxd:.1f} MS", (160, 160, 180))
    prop = ServerObjects()
    prop.raw_body = img.png_bytes()
    prop.raw_ctype = "image/png"
    return prop
