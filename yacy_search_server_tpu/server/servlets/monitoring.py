"""Monitoring + inspection servlets: memory dashboard, crawl results,
cached-page viewer, profiling graph.

Capability equivalents of the reference's operations pages (reference:
htroot/PerformanceMemory_p.java — heap/tables memory dashboard backed by
MemoryControl; htroot/CrawlResults.java — per-origin crawl outcome lists
incl. the error cache; htroot/ViewFile.java — render a cached page's
text/metadata from the HTCache; htroot/PerformanceGraph.java — the
EventTracker time-series rendered as a PNG via ProfilingGraph)."""

from __future__ import annotations

import os

from ...utils import histogram, tracing
from ...utils.eventtracker import EClass, events
from ...utils.memory import MemoryControl
from ..objects import ServerObjects, escape_json
from . import servlet


@servlet("PerformanceMemory_p")
def respond_memory(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("used_bytes", MemoryControl.used())
    prop.put("available_bytes", MemoryControl.available())
    prop.put("short_status", 1 if MemoryControl.short_status() else 0)
    # per-store accounting (the reference's table/heap trackers)
    rows = [
        ("rwi.ram_postings", sb.index.rwi.ram_postings_count),
        ("rwi.total_postings", sb.index.rwi.total_postings()),
        ("rwi.runs", sb.index.rwi.run_count()),
        ("metadata.docs", len(sb.index.metadata)),
        ("search.cached_events", len(sb.search_cache)),
        ("frontier.local", _frontier_size(sb)),
        ("tables", len(sb.tables.tables())),
    ]
    prop.put("stores", len(rows))
    for i, (name, v) in enumerate(rows):
        prop.put(f"stores_{i}_name", name)
        prop.put(f"stores_{i}_value", int(v))
    return prop


def _frontier_size(sb) -> int:
    from ...crawler.frontier import StackType
    return sb.noticed.size(StackType.LOCAL)


@servlet("CrawlResults")
def respond_crawl_results(header: dict, post: ServerObjects,
                          sb) -> ServerObjects:
    prop = ServerObjects()
    prop.put("indexed_count", sb.indexed_count)
    errors = sb.crawl_queues.error_cache.recent(post.get_int("count", 50))
    prop.put("errors", len(errors))
    for i, (url, reason, ts) in enumerate(errors):
        prop.put(f"errors_{i}_url", escape_json(url))
        prop.put(f"errors_{i}_reason", escape_json(reason))
        prop.put(f"errors_{i}_time", int(ts))
    return prop


@servlet("ViewFile")
def respond_viewfile(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Inspect a document as the index sees it: cached raw content,
    extracted text, or metadata row (ViewFile.java viewMode semantics)."""
    prop = ServerObjects()
    url = post.get("url", "")
    mode = post.get("viewMode", "parsed")
    if not url:
        prop.put("info", "missing url")
        return prop
    from ...utils.hashes import url2hash
    docid = sb.index.metadata.docid(url2hash(url))
    if mode == "raw":
        got = sb.htcache.get(url)
        if got is None:
            prop.put("info", "not in cache")
            return prop
        content, headers = got
        prop.raw_body = content
        prop.raw_ctype = headers.get("content-type",
                                     "application/octet-stream")
        return prop
    if docid is None:
        prop.put("info", "not indexed")
        return prop
    m = sb.index.metadata.get(docid)
    prop.put("url", escape_json(url))
    prop.put("title", escape_json(m.get("title", "")))
    prop.put("docid", docid)
    if mode == "metadata":
        for k, v in sorted(m.fields.items()):
            if k != "text_t":
                prop.put(f"field_{k}", escape_json(str(v)))
    else:   # parsed text
        prop.put("text", escape_json(m.get("text_t", "")[:20000]))
        prop.put("wordcount", m.get("wordcount_i", 0))
    return prop


# lint: trace-ok(renders PROFILER aggregates to a dashboard; serves no
# query and measures no request wall of its own)
@servlet("Performance_Roofline_p")
def respond_roofline(header: dict, post: ServerObjects,
                     sb) -> ServerObjects:
    """Silicon accounting dashboard (ISSUE 1): every serving kernel's
    achieved FLOP/s / GB/s placed against the device roofline, plus the
    per-query utilization percentiles the rank-service counters carry.
    `format=png` renders the log-log roofline chart via the raster
    layer; the default response is the numeric table (template/API
    form, like DeviceStore_p)."""
    from ...ops import roofline as RF
    from ...utils.profiler import PROFILER

    peak = PROFILER.peak
    points = PROFILER.snapshot()
    if post.get("format", "") == "png":
        prop = ServerObjects()
        prop.raw_body = _roofline_png(points, peak)
        prop.raw_ctype = "image/png"
        return prop
    prop = ServerObjects()
    prop.put("device", escape_json(peak.name))
    prop.put("peak_tflops", round(peak.flops_per_s / 1e12, 3))
    prop.put("peak_gbps", round(peak.bytes_per_s / 1e9, 1))
    prop.put("ridge_flops_per_byte", round(peak.ridge, 2))
    util = PROFILER.query_util()
    prop.put("util_pct_p50", util["util_pct_p50"])
    prop.put("util_pct_p95", util["util_pct_p95"])
    prop.put("bound", util["bound"])
    prop.put("kernels", len(points))
    for i, p in enumerate(points):
        prop.put(f"kernels_{i}_name", p.kernel)
        prop.put(f"kernels_{i}_gflops", round(p.flops / 1e9, 3))
        prop.put(f"kernels_{i}_mbytes", round(p.bytes / 1e6, 2))
        prop.put(f"kernels_{i}_intensity", round(p.intensity, 2))
        prop.put(f"kernels_{i}_achieved_gflops_s",
                 round(p.achieved_flops_per_s / 1e9, 3))
        prop.put(f"kernels_{i}_achieved_gbytes_s",
                 round(p.achieved_bytes_per_s / 1e9, 3))
        prop.put(f"kernels_{i}_bound", p.bound)
        prop.put(f"kernels_{i}_util_pct", p.util_pct)
    return prop


def _roofline_png(points, peak, w: int = 640, h: int = 360) -> bytes:
    """Log-log roofline: the memory-bandwidth diagonal and the compute
    ceiling, with one dot per profiled kernel at (intensity, achieved
    FLOP/s)."""
    import math

    from ...visualization.raster import RasterPlotter
    img = RasterPlotter(w, h, background=(10, 10, 30))
    x0, y0, x1, y1 = 56, 24, w - 16, h - 44
    lx_min, lx_max = -2.0, 4.0                 # intensity 0.01..10^4 f/B
    ly_max = math.log10(max(peak.flops_per_s, 1.0))
    ly_min = ly_max - 8.0                      # 8 decades of FLOP/s

    def px(v):
        lv = min(max(math.log10(max(v, 1e-9)), lx_min), lx_max)
        return int(x0 + (lv - lx_min) / (lx_max - lx_min) * (x1 - x0))

    def py(v):
        lv = min(max(math.log10(max(v, 1.0)), ly_min), ly_max)
        return int(y1 - (lv - ly_min) / (ly_max - ly_min) * (y1 - y0))

    img.rect(x0, y0, x1, y1, (60, 60, 90))
    # the two roofs meet at the ridge point
    ridge = peak.ridge
    img.line(px(10 ** lx_min), py(10 ** lx_min * peak.bytes_per_s),
             px(ridge), py(peak.flops_per_s), (230, 180, 60))
    img.line(px(ridge), py(peak.flops_per_s),
             px(10 ** lx_max), py(peak.flops_per_s), (230, 180, 60))
    img.text(x0 + 4, y0 + 4,
             f"{peak.name}  {peak.flops_per_s / 1e12:.0f} TF/S  "
             f"{peak.bytes_per_s / 1e9:.0f} GB/S", (200, 200, 220))
    for i, p in enumerate(points):
        x, y = px(p.intensity), py(p.achieved_flops_per_s)
        color = (120, 200, 255) if p.bound == "memory" else (255, 140, 160)
        img.dot(x, y, color, radius=3)
        img.text(min(x + 6, w - 120), max(y - 4, y0 + 2),
                 f"{p.kernel[:16].upper()} {p.util_pct:.1f}", color)
    img.text(x0, h - 32, "X: FLOPS/BYTE   Y: FLOP/S   "
             "BLUE: MEMORY-BOUND  RED: COMPUTE-BOUND", (160, 160, 180))
    return img.png_bytes()


@servlet("PerformanceGraph")
def respond_perfgraph(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """EventTracker time-series as a PNG bar graph (ProfilingGraph)."""
    from ...visualization.raster import RasterPlotter
    try:
        ecl = EClass[post.get("set", "SEARCH").upper()]
    except KeyError:
        ecl = EClass.SEARCH
    evs = events(ecl)[-60:]
    w, h = 640, 240
    img = RasterPlotter(w, h, background=(10, 10, 30))
    img.text(8, 6, f"{ecl.name} EVENTS: {len(evs)}", (200, 200, 220))
    if evs:
        maxd = max(max(e.duration_ms for e in evs), 1.0)
        bw = max(2, (w - 20) // max(len(evs), 1))
        for i, e in enumerate(evs):
            bh = int((e.duration_ms / maxd) * (h - 60))
            x = 10 + i * bw
            img.rect(x, h - 20 - bh, x + bw - 2, h - 20,
                     (90, 200, 140), fill=True)
        img.text(8, h - 12, f"MAX {maxd:.1f} MS", (160, 160, 180))
    prop = ServerObjects()
    prop.raw_body = img.png_bytes()
    prop.raw_ctype = "image/png"
    return prop


# ---------------------------------------------------------------------------
# distributed tracing surface (ISSUE 2)
# ---------------------------------------------------------------------------

# span-name prefix -> waterfall bar color (one hue per layer)
_TRACE_COLORS = [
    ("servlet.", (120, 200, 255)),
    ("switchboard.", (160, 220, 160)),
    ("search.", (90, 200, 140)),
    ("devstore.", (255, 190, 90)),
    ("mesh.", (255, 190, 90)),
    ("kernel.", (255, 140, 160)),
    ("peers.", (200, 160, 255)),
    ("peer.", (200, 160, 255)),
    ("index.", (180, 180, 120)),
]


def _span_color(name: str):
    for prefix, color in _TRACE_COLORS:
        if name.startswith(prefix):
            return color
    return (170, 170, 190)


@servlet("Performance_Trace_p")
def respond_trace(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Per-request stage attribution (ISSUE 2): the recent-trace table,
    per-stage p50/p95 with the tail-dominant stage named, and — for one
    trace — the span list or a waterfall PNG rendered on the raster
    layer. `format=jsonl` exports the retained ring for offline
    analysis."""
    fmt = post.get("format", "")
    tid = post.get("trace", "")
    if fmt == "jsonl":
        prop = ServerObjects()
        prop.raw_body = tracing.export_jsonl(post.get_int("count", 50))
        prop.raw_ctype = "application/jsonl; charset=utf-8"
        return prop
    if tid and fmt == "png":
        rec = tracing.get_trace(tid)
        prop = ServerObjects()
        prop.raw_body = _trace_waterfall_png(rec)
        prop.raw_ctype = "image/png"
        return prop
    prop = ServerObjects()
    prop.put("enabled", 1 if tracing.enabled() else 0)
    prop.put("dropped_traces", tracing.dropped_traces)
    prop.put("dropped_spans", tracing.dropped_spans)
    if tid:
        # cross-peer assembly (ISSUE 5): fetch the trace's remote
        # segments out of the asked peers' rings and merge them here, so
        # the waterfall below shows the WHOLE distributed request
        # instead of an opaque resource=global gap
        if post.get("assemble", "") == "1":
            node = getattr(sb, "node", None)
            prop.put("assembled_spans",
                     node.assemble_trace(tid) if node is not None else 0)
        rec = tracing.get_trace(tid)
        if rec is None:
            prop.put("info", "unknown trace")
            prop.put("spans", 0)
            return prop
        prop.put("trace_id", escape_json(rec.trace_id))
        prop.put("root", escape_json(rec.root_name))
        prop.put("duration_ms", round(rec.duration_ms(), 3))
        t0 = min((s.ts for s in rec.spans), default=rec.created)
        prop.put("spans", len(rec.spans))
        for i, s in enumerate(rec.spans):
            p = f"spans_{i}_"
            prop.put(p + "name", escape_json(s.name))
            prop.put(p + "offset_ms", round((s.ts - t0) * 1000.0, 3))
            prop.put(p + "dur_ms", round(s.dur_ms, 3))
            prop.put(p + "parent", escape_json(s.parent))
            prop.put(p + "attrs", escape_json(
                " ".join(f"{k}={v}" for k, v in s.attrs.items())))
        return prop
    recs = tracing.traces(post.get_int("count", 25))
    prop.put("traces", len(recs))
    for i, rec in enumerate(recs):
        p = f"traces_{i}_"
        prop.put(p + "trace_id", escape_json(rec.trace_id))
        prop.put(p + "root", escape_json(rec.root_name))
        prop.put(p + "duration_ms", round(rec.duration_ms(), 3))
        prop.put(p + "spans", len(rec.spans))
        prop.put(p + "done", 1 if rec.done else 0)
    # serving-stage summary by default; workload=all folds the sampled
    # per-document pipeline stages in too.  Answered from the WINDOWED
    # histograms (ISSUE 4 satellite): the old path re-walked every span
    # of the 256-trace ring per page load to recompute the same p50/p95
    # the histograms now maintain incrementally — and these percentiles
    # cover the last ~3 minutes of the whole workload, not whatever the
    # ring happens to retain
    summary = histogram.stage_table(
        exclude_prefixes=() if post.get("workload", "") == "all"
        else histogram.BACKGROUND_PREFIXES)
    stages = sorted(summary["stages"].items(),
                    key=lambda kv: -kv[1]["p95_ms"])
    prop.put("tail_dominant_stage",
             escape_json(summary["tail_dominant_stage"]))
    prop.put("stages", len(stages))
    for i, (name, st) in enumerate(stages):
        p = f"stages_{i}_"
        prop.put(p + "name", escape_json(name))
        prop.put(p + "count", st["count"])
        prop.put(p + "p50_ms", st["p50_ms"])
        prop.put(p + "p95_ms", st["p95_ms"])
    return prop


def _trace_waterfall_png(rec, w: int = 760, h: int = 0) -> bytes:
    """One trace as a waterfall: a bar per span, x = offset within the
    trace, width = duration, one color per layer prefix."""
    from ...visualization.raster import RasterPlotter
    spans = sorted(rec.spans, key=lambda s: s.ts) if rec else []
    row_h = 14
    h = h or max(80, 48 + row_h * len(spans))
    img = RasterPlotter(w, h, background=(10, 10, 30))
    if rec is None or not spans:
        img.text(8, 8, "NO SUCH TRACE / NO SPANS", (200, 200, 220))
        return img.png_bytes()
    t0 = min(s.ts for s in spans)
    t1 = max(s.ts + s.dur_ms / 1000.0 for s in spans)
    total_ms = max((t1 - t0) * 1000.0, 1e-3)
    img.text(8, 6, f"TRACE {rec.trace_id}  {total_ms:.1f} MS  "
             f"{len(spans)} SPANS", (200, 200, 220))
    x0, x1 = 200, w - 12
    for i, s in enumerate(spans):
        y = 28 + i * row_h
        color = _span_color(s.name)
        img.text(8, y, s.name[:24].upper(), color)
        bx0 = x0 + int((s.ts - t0) * 1000.0 / total_ms * (x1 - x0))
        bx1 = bx0 + max(2, int(s.dur_ms / total_ms * (x1 - x0)))
        img.rect(bx0, y + 2, min(bx1, x1), y + row_h - 4, color,
                 fill=True)
    img.text(8, h - 12, f"SCALE: {total_ms:.1f} MS ACROSS", (160, 160, 180))
    return img.png_bytes()


# ---------------------------------------------------------------------------
# /metrics — Prometheus text exposition (ISSUE 2): one endpoint unifying
# every counter the codebase keeps but scatters
# ---------------------------------------------------------------------------


def _prom_escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class _Prom:
    """Tiny exposition builder: families declared once, samples appended
    in declaration order (the text-format contract: all samples of a
    family are consecutive, HELP/TYPE precede them).  In OpenMetrics
    mode counter families are declared on the suffix-free base name
    (the spec reserves `_total` for the sample and forbids it on the
    family), and only then may bucket samples carry exemplars."""

    def __init__(self, openmetrics: bool = False):
        self.lines: list[str] = []
        self.openmetrics = openmetrics

    def family(self, name: str, kind: str, help_: str):
        if self.openmetrics and kind == "counter" \
                and name.endswith("_total"):
            name = name[:-len("_total")]
        self.lines.append(f"# HELP {name} {help_}")
        self.lines.append(f"# TYPE {name} {kind}")

    def sample(self, name: str, value, labels: dict | None = None,
               exemplar: tuple | None = None):
        if labels:
            lbl = ",".join(f'{k}="{_prom_escape(v)}"'
                           for k, v in labels.items())
            name = f"{name}{{{lbl}}}"
        if isinstance(value, float):
            value = round(value, 6)
        line = f"{name} {value}"
        if exemplar is not None:
            # OpenMetrics exemplar syntax: `# {trace_id="..."} value ts`
            # — the link from a slow histogram bucket straight to its
            # Performance_Trace_p waterfall (ISSUE 4)
            tid, ex_v, ex_ts = exemplar
            line += (f' # {{trace_id="{_prom_escape(tid)}"}} '
                     f"{round(ex_v, 6)} {round(ex_ts, 3)}")
        self.lines.append(line)

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def prometheus_text(sb, include_buckets: bool = True,
                    openmetrics: bool = False) -> str:
    """Assemble the node's unified metric surface: eventtracker series,
    roofline utilization, device/mesh batcher health (incl. the
    queue_full/flush_deadline/worker_stall cause buckets), crawler
    queue depths, pipeline stages, DHT transfer counts, the logging
    drop counter (counted at utils/logging.py but surfaced nowhere
    until now), the windowed latency histograms and the tracing ring's
    own accounting.  `include_buckets=False` skips the per-bucket
    histogram samples (every family still exposes `_sum`/`_count`) —
    the health tick's evaluation surface, which reads no buckets and
    must stay cheap at its 5 s cadence.  `openmetrics=True` switches to
    the OpenMetrics dialect: suffix-free counter family declarations,
    `# {trace_id=...}` bucket exemplars and the `# EOF` trailer —
    features the classic 0.0.4 expfmt parser rejects, so they never
    appear on the default form."""
    from ...crawler.frontier import StackType
    from ...utils import logging as ylog
    from ...utils.eventtracker import totals
    from ...utils.profiler import PROFILER

    p = _Prom(openmetrics=openmetrics)

    p.family("yacy_log_dropped_records_total", "counter",
             "log records dropped by the bounded async logging queue")
    p.sample("yacy_log_dropped_records_total", ylog.dropped_count())

    p.family("yacy_stage_events_total", "counter",
             "eventtracker stage executions per (class,label)")
    tot = totals()
    for (ecl, label), (n_ev, _items, _ms) in sorted(
            tot.items(), key=lambda kv: (kv[0][0].value, kv[0][1])):
        p.sample("yacy_stage_events_total", n_ev,
                 {"class": ecl.value, "label": label})
    p.family("yacy_stage_duration_ms_total", "counter",
             "cumulative wall per eventtracker stage")
    for (ecl, label), (_n, _items, ms) in sorted(
            tot.items(), key=lambda kv: (kv[0][0].value, kv[0][1])):
        p.sample("yacy_stage_duration_ms_total", ms,
                 {"class": ecl.value, "label": label})

    util = PROFILER.query_util()
    p.family("yacy_roofline_util_pct", "gauge",
             "per-query achieved utilization vs device peak")
    p.sample("yacy_roofline_util_pct", util["util_pct_p50"],
             {"quantile": "p50"})
    p.sample("yacy_roofline_util_pct", util["util_pct_p95"],
             {"quantile": "p95"})
    p.family("yacy_roofline_kernel_util_pct", "gauge",
             "per-kernel achieved utilization vs device peak")
    for pt in PROFILER.snapshot():
        p.sample("yacy_roofline_kernel_util_pct", pt.util_pct,
                 {"kernel": pt.kernel, "bound": pt.bound})

    # device families are emitted even when no device store serves (all
    # zeros): the health rules reference these series by exact key, and
    # the no-dead-rules hygiene gate requires every reference to resolve
    # on every node configuration
    ds = sb.index.devstore
    c = ds.counters() if ds is not None else {}
    p.family("yacy_batch_timeouts_total", "counter",
             "batcher watchdog timeouts by cause bucket "
             "(worker_stall must stay 0 in healthy serving)")
    for cause in ("queue_full", "flush_deadline", "worker_stall"):
        p.sample("yacy_batch_timeouts_total",
                 c.get(f"batch_timeout_{cause}", 0), {"cause": cause})
    p.family("yacy_device_serving_total", "counter",
             "device store serving counters")
    for key in ("queries_served", "fallbacks", "stream_scans",
                "filtered_served", "join_served", "join_fallbacks",
                "batch_dispatches", "batch_exceptions",
                "batch_ineligible", "prune_rounds",
                # versioned top-k result cache (hits serve with zero
                # device work; stale = correct epoch invalidations)
                "rank_cache_hits", "rank_cache_stale",
                # batched hybrid rerank: queries/dispatches = mean
                # coalescing factor; cache hits = full hybrid answers
                # served without touching the device
                "rerank_dispatches", "rerank_queries",
                "rerank_cache_hits", "rerank_fallbacks",
                # tier ladder hit attribution (compressed residency)
                "tier_hot_hits", "tier_warm_hits", "tier_cold_hits",
                "device_round_trips"):
        p.sample("yacy_device_serving_total", c.get(key, 0),
                 {"counter": key})
    # HBM accounting for the fleet (ISSUE 8 satellite): per-tier byte
    # occupancy and the promotion/demotion flow — always emitted (zeros
    # without a devstore) so the fleet digest's tier fields and any
    # future health rule resolve on every node configuration
    p.family("yacy_device_hbm_bytes", "gauge",
             "postings bytes resident per tier (hot=device packed/int16, "
             "warm=host-RAM packed blocks, cold=paged-run mmap), plus "
             "the vector side (ISSUE 11): dense=f16 forward-index "
             "block, ann_hot/warm/cold=the IVF slab ladder — every "
             "resident byte accounted")
    for tier in ("hot", "warm", "cold"):
        p.sample("yacy_device_hbm_bytes", c.get(f"tier_{tier}_bytes", 0),
                 {"tier": tier})
    p.sample("yacy_device_hbm_bytes", c.get("dense_fwd_bytes", 0),
             {"tier": "dense"})
    for tier in ("hot", "warm", "cold"):
        p.sample("yacy_device_hbm_bytes",
                 c.get(f"ann_{tier}_bytes", 0), {"tier": f"ann_{tier}"})
    # dense-first IVF ANN (ISSUE 11): candidate-generation coverage +
    # the vector tier ladder's traffic — always emitted (zeros without
    # an index) so fleet digests and health rules resolve everywhere
    p.family("yacy_ann_total", "counter",
             "dense-first ANN counters: queries/dispatches = mean "
             "coalescing factor, host_queries = device-loss host path, "
             "fallbacks = no index (plain rerank served), tier hits = "
             "probe traffic per residency tier, promotions = clusters "
             "uploaded into the hot arena, lane_drops = whole-cluster "
             "probe-budget drops")
    for key in ("ann_dispatches", "ann_queries", "ann_fallbacks",
                "ann_host_queries", "ann_tier_hot_hits",
                "ann_tier_warm_hits", "ann_tier_cold_hits",
                "ann_promotions", "ann_promote_failures",
                "ann_lane_drops"):
        p.sample("yacy_ann_total", c.get(key, 0),
                 {"counter": key[4:]})
    p.family("yacy_ann_centroid_version", "gauge",
             "ANN centroid-set version (bumps on rebuild AND on hot "
             "promotion — scoring-venue moves re-key cached fused "
             "lists; keys the dense-first top-k cache)")
    p.sample("yacy_ann_centroid_version",
             c.get("ann_centroid_version", 0))
    p.family("yacy_ann_resident_vectors", "gauge",
             "vectors resident in the IVF slab ladder")
    p.sample("yacy_ann_resident_vectors", c.get("ann_vectors", 0))
    p.family("yacy_tier_promotions_total", "counter",
             "tier ladder transitions (src->dst; demotions/evictions "
             "ride the same family)")
    for src, dst, key in (("warm", "hot", "tier_promotions_warm_hot"),
                          ("cold", "hot", "tier_promotions_cold_hot"),
                          ("hot", "warm", "tier_demotions_hot_warm"),
                          ("warm", "cold", "tier_evictions_warm_cold")):
        p.sample("yacy_tier_promotions_total", c.get(key, 0),
                 {"src": src, "dst": dst})
    p.family("yacy_device_compression_ratio", "gauge",
             "measured int16-bytes/packed-bytes over resident packed "
             "blocks (1.0 = int16 residency)")
    p.sample("yacy_device_compression_ratio",
             c.get("packed_compression_ratio", 1.0))
    # cold-tier paging cache (index/pagedrun.TermCache): byte-budget LRU
    # behavior must be attributable when paging storms hit the host path
    p.family("yacy_term_cache_total", "counter",
             "paged-run term cache events (the cold tier's LRU)")
    for ev in ("hits", "misses", "evictions"):
        p.sample("yacy_term_cache_total", c.get(f"term_cache_{ev}", 0),
                 {"event": ev})
    p.family("yacy_term_cache_bytes", "gauge",
             "resident bytes in the paged-run term cache")
    p.sample("yacy_term_cache_bytes", c.get("term_cache_bytes", 0))
    p.family("yacy_device_arena_epoch", "gauge",
             "arena epoch (bumps on flush/merge/repack/delete; the "
             "stale-spike health rule reads its churn)")
    p.sample("yacy_device_arena_epoch", c.get("arena_epoch", 0))
    # -- multi-process mesh identity (ISSUE 12): which OS process this
    # node is.  Always emitted (pid everywhere; process_id/num_processes
    # zero-filled off-mesh) so the fleet digest's proc fields resolve on
    # every node configuration — the coordinator's Network_Health_p
    # renders the REAL process grid from its peers' digests.
    mm = getattr(sb, "mesh_member", None)
    p.family("yacy_mesh_process", "gauge",
             "multi-process mesh identity: this node's OS pid, its "
             "jax.distributed process id and the mesh process count "
             "(0/1 when not a mesh member)")
    p.sample("yacy_mesh_process", os.getpid(), {"field": "pid"})
    p.sample("yacy_mesh_process",
             mm.process_id if mm is not None else 0,
             {"field": "process_id"})
    p.sample("yacy_mesh_process",
             mm.num_processes if mm is not None else 1,
             {"field": "num_processes"})
    # -- device-loss recovery (ISSUE 10c): always emitted (zeros
    # without a devstore) — the device_loss health rule and the
    # device_rebuild actuator reference these series by exact key
    p.family("yacy_device_lost", "gauge",
             "1 while the device is declared lost (queries host-"
             "fallback, background rebuild running), else 0")
    p.sample("yacy_device_lost", c.get("device_lost", 0))
    p.family("yacy_device_loss_total", "counter",
             "device-loss lifecycle counters: declared losses, "
             "completed rebuilds back to device serving, host-fallback "
             "answers while lost, retry-exhausted transfer failures, "
             "bounded in-ladder transfer retries")
    for key in ("losses", "recoveries", "lost_queries",
                "transfer_failures", "transfer_retries"):
        ck = {"losses": "device_losses",
              "recoveries": "device_loss_recoveries",
              "lost_queries": "device_lost_queries"}.get(key, key)
        p.sample("yacy_device_loss_total", c.get(ck, 0),
                 {"event": key})
    # -- read-side integrity (ISSUE 10a): corruption detections by
    # (kind, action) and journal torn-tail recoveries per store —
    # zero-filled over the canonical sets so alert expressions and the
    # storage_corruption rule always resolve
    from ...index import integrity as _integ
    p.family("yacy_storage_corruption_total", "counter",
             "storage corruption events: kind=run/segment/journal, "
             "action=error (detection) / quarantined (run pulled from "
             "serving, terms answered from surviving generations)")
    for (kind, action), v in sorted(_integ.corruption_counts().items()):
        p.sample("yacy_storage_corruption_total", v,
                 {"kind": kind, "action": action})
    p.family("yacy_journal_torn_tail_total", "counter",
             "journal replays that dropped a torn tail line (the "
             "expected kill-9 artifact: recovered, counted)")
    for store, v in sorted(_integ.torn_tail_counts().items()):
        p.sample("yacy_journal_torn_tail_total", v, {"store": store})
    p.family("yacy_integrity_verified_total", "counter",
             "checksum verifications performed on the read path "
             "(spans, segment columns, run indexes)")
    p.sample("yacy_integrity_verified_total", _integ.verified_total())
    p.family("yacy_batcher_queue_depth", "gauge",
             "batcher incoming / in-flight queue depths (the backlog "
             "health rule watches the growth trend)")
    b = getattr(ds, "_batcher", None) if ds is not None else None
    p.sample("yacy_batcher_queue_depth",
             b._q.qsize() if b is not None else 0, {"queue": "incoming"})
    p.sample("yacy_batcher_queue_depth",
             b._inflight.qsize() if b is not None else 0,
             {"queue": "inflight"})
    if ds is not None:
        p.family("yacy_device_latency_ms", "gauge",
                 "per-query dispatch/kernel wall percentiles")
        for key in ("dispatch_ms_p50", "dispatch_ms_p95",
                    "kernel_ms_p50", "kernel_ms_p95", "tunnel_rt_ms"):
            if key in c:
                p.sample("yacy_device_latency_ms", c[key], {"stat": key})

    p.family("yacy_crawler_queue_depth", "gauge",
             "frontier stack depths")
    for stack in (StackType.LOCAL, StackType.GLOBAL, StackType.REMOTE,
                  StackType.NOLOAD):
        p.sample("yacy_crawler_queue_depth", sb.noticed.size(stack),
                 {"stack": stack})

    p.family("yacy_pipeline_processed_total", "counter",
             "documents through each indexing pipeline stage")
    p.family("yacy_pipeline_errors_total", "counter",
             "stage handler errors")
    p.family("yacy_pipeline_queued", "gauge", "stage queue depth")
    procs = [sb._parse_proc, sb._condense_proc, sb._structure_proc,
             sb._store_proc]
    for proc in procs:
        p.sample("yacy_pipeline_processed_total", proc.metrics.processed,
                 {"stage": proc.name})
    for proc in procs:
        p.sample("yacy_pipeline_errors_total", proc.metrics.errors,
                 {"stage": proc.name})
    for proc in procs:
        p.sample("yacy_pipeline_queued", proc.queue.qsize(),
                 {"stage": proc.name})

    p.family("yacy_index_documents", "gauge", "documents in the index")
    p.sample("yacy_index_documents", sb.index.doc_count())
    p.family("yacy_index_rwi_postings", "gauge",
             "postings in the reverse word index")
    p.sample("yacy_index_rwi_postings", sb.index.rwi_size())
    p.family("yacy_search_cached_events", "gauge",
             "live events in the search event cache")
    p.sample("yacy_search_cached_events", len(sb.search_cache))
    p.family("yacy_indexed_documents_total", "counter",
             "documents stored by this node since start")
    p.sample("yacy_indexed_documents_total", sb.indexed_count)

    node = getattr(sb, "node", None)
    if node is not None:
        p.family("yacy_dht_transferred_postings_total", "counter",
                 "postings shipped to DHT target peers")
        p.sample("yacy_dht_transferred_postings_total",
                 node.dispatcher.transferred_postings)
        p.family("yacy_dht_received_total", "counter",
                 "index transfer receipts by kind")
        p.sample("yacy_dht_received_total", node.server.received_rwi_count,
                 {"kind": "rwi"})
        p.sample("yacy_dht_received_total", node.server.received_url_count,
                 {"kind": "url"})
        p.family("yacy_peers", "gauge", "seed directory population")
        p.sample("yacy_peers", len(node.seeddb.active), {"state": "active"})
        p.sample("yacy_peers", len(node.seeddb.passive),
                 {"state": "passive"})
        p.sample("yacy_peers", len(node.seeddb.potential),
                 {"state": "potential"})

    # -- fleet observability (ISSUE 5): the coordinator-free mesh view.
    # Emitted on EVERY node (zeros without peers): the fleet_* health
    # rules reference these series by exact key, and the no-dead-rules
    # hygiene gate requires every reference to resolve everywhere.
    from ...utils import fleet as fleetdigest
    fl = getattr(sb, "fleet", None)
    if fl is not None:
        fl.render()       # keep the digest-size gauge honest per scrape
    peers_fresh = fl.fresh() if fl is not None else []
    p.family("yacy_fleet_peers", "gauge",
             "fresh peer metric digests retained in the fleet table")
    p.sample("yacy_fleet_peers", len(peers_fresh))
    p.family("yacy_fleet_digests_total", "counter",
             "digest gossip traffic (rendered locally, received from "
             "peers, ignored as invalid/replayed)")
    for kind, v in (("rendered", fl.rendered_count if fl else 0),
                    ("received", fl.received_count if fl else 0),
                    ("ignored", fl.ignored_count if fl else 0)):
        p.sample("yacy_fleet_digests_total", v, {"kind": kind})
    p.family("yacy_fleet_digest_bytes", "gauge",
             "wire size of the last rendered local digest "
             "(budget: fleet.byteBudget, default 2048)")
    p.sample("yacy_fleet_digest_bytes",
             fl.last_digest_bytes if fl else 0)
    p.family("yacy_fleet_merged_latency_ms", "gauge",
             "mesh-wide percentiles from merged local+peer digest "
             "bucket vectors (lossless merge, no coordinator)")
    for fam in fleetdigest.DIGEST_FAMILIES:
        counts = fl.merged_counts(fam) if fl is not None else None
        for q, lbl in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = histogram.percentile_from_counts(counts, q) \
                if counts else 0.0
            p.sample("yacy_fleet_merged_latency_ms", round(v, 3),
                     {"family": fam, "quantile": lbl})
    p.family("yacy_fleet_peer_reported_critical", "gauge",
             "fresh peers whose digest reports critical health")
    p.sample("yacy_fleet_peer_reported_critical",
             len([e for e in peers_fresh if e.get("health") == 2]))

    # -- tail forensics (ISSUE 15): the cause-attribution canon.  Every
    # over-threshold serving query gets exactly one classified verdict;
    # the cause counters are ZERO-FILLED over the canon so alert
    # expressions and the fleet digest's top-1 mapping always resolve.
    from ...utils import tailattr
    p.family("yacy_tail_cause_total", "counter",
             "classified p99 verdicts by dominant cause (one verdict "
             "per over-threshold serving query; collective_straggler "
             "verdicts additionally name the member in "
             "yacy_tail_straggler_total)")
    tc = tailattr.cause_totals()
    for cause in tailattr.CAUSES:
        p.sample("yacy_tail_cause_total", tc.get(cause, 0),
                 {"cause": cause})
    p.family("yacy_tail_straggler_total", "counter",
             "collective_straggler verdicts by the named mesh member")
    for member, v in sorted(tailattr.straggler_totals().items()):
        p.sample("yacy_tail_straggler_total", v, {"member": member})
    # straggler convictions (ISSUE 19 / ROADMAP 1c, read-only): the
    # member was the slowest leg over N consecutive scoreboard windows.
    # ZERO-FILLED over every member the coordinator's timeline has
    # scattered to, so alert expressions resolve before (and without)
    # any conviction ever firing.
    p.family("yacy_mesh_straggler_convictions_total", "counter",
             "straggler-scoreboard convictions (member slowest over N "
             "consecutive windows; observation only — no steering)")
    for member, v in sorted(tailattr.conviction_totals().items()):
        p.sample("yacy_mesh_straggler_convictions_total", v,
                 {"member": member})
    p.family("yacy_tail_verdicts_total", "counter",
             "over-threshold serving queries classified by the "
             "tail-attribution engine")
    p.sample("yacy_tail_verdicts_total",
             tailattr.ATTR.counters()["classified_total"])

    # -- whitebox profiler (ISSUE 20): sampler counters + per-role
    # sample totals ZERO-FILLED over the profiling.ROLES canon (the
    # fleet digest's top-role index maps into these, so the series must
    # resolve on every node before any sampling happens)
    from ...utils import profiling
    pstats = profiling.stats()
    p.family("yacy_prof_samples_total", "counter",
             "thread-stack samples folded by the in-process profiler")
    p.sample("yacy_prof_samples_total", pstats["samples_total"])
    p.family("yacy_prof_capture_windows_total", "counter",
             "triggered high-rate deep-capture windows completed")
    p.sample("yacy_prof_capture_windows_total",
             pstats["capture_windows_total"])
    p.family("yacy_prof_holder_captures_total", "counter",
             "over-p95 lock holds whose holder stack was captured")
    p.sample("yacy_prof_holder_captures_total",
             pstats["holder_captures_total"])
    p.family("yacy_prof_sampler_hz", "gauge",
             "current profiler sampling cadence (burst while a "
             "capture window is armed)")
    p.sample("yacy_prof_sampler_hz", round(pstats["sampler_hz"], 1))
    p.family("yacy_prof_role_samples_total", "counter",
             "profiler samples by thread role (named-pool canon; "
             "windowed over the retained sample ring)")
    samp = profiling.sampler()
    roles = samp.role_samples() if samp is not None \
        else {r: 0 for r in profiling.ROLES}
    for role in profiling.ROLES:
        p.sample("yacy_prof_role_samples_total", roles.get(role, 0),
                 {"role": role})

    p.family("yacy_traces_retained", "gauge",
             "completed traces in the tracing ring")
    p.sample("yacy_traces_retained", len(tracing.traces(tracing.MAX_TRACES)))
    p.family("yacy_trace_drops_total", "counter",
             "traces/spans dropped at the ring bounds")
    p.sample("yacy_trace_drops_total", tracing.dropped_traces,
             {"kind": "traces"})
    p.sample("yacy_trace_drops_total", tracing.dropped_spans,
             {"kind": "spans"})

    # -- windowed latency histograms (ISSUE 4): one Prometheus histogram
    # family per registered Histogram — cumulative _bucket/_sum/_count
    # (monotonic by contract) with trace-id exemplars on the buckets the
    # slow requests landed in.  EVERY registered histogram appears here
    # by construction (iterating the registry is the hygiene gate).
    for h in histogram.all_histograms():
        fam = histogram.prom_name(h.name)
        snap = h.snapshot()
        p.family(fam, "histogram", h.help)
        if include_buckets:
            exs = snap["exemplars"] if openmetrics \
                else [None] * len(snap["exemplars"])
            cum = 0
            for i, le in enumerate(histogram.BUCKET_BOUNDS_MS):
                cum += snap["counts"][i]
                p.sample(fam + "_bucket", cum, {"le": f"{le:g}"},
                         exemplar=exs[i])
            cum += snap["counts"][-1]
            p.sample(fam + "_bucket", cum, {"le": "+Inf"},
                     exemplar=exs[-1])
        p.sample(fam + "_sum", round(snap["sum_ms"], 3))
        p.sample(fam + "_count", snap["count"])

    # -- health engine (ISSUE 4): the overall gauge + one gauge per rule
    # (0 ok / 1 warn / 2 critical) so an alertmanager can page on the
    # same states Performance_Health_p shows
    eng = getattr(sb, "health", None)
    if eng is not None:
        p.family("yacy_health_status", "gauge",
                 "overall node health (0 ok / 1 warn / 2 critical)")
        p.sample("yacy_health_status", eng.status_value())
        p.family("yacy_health_rule", "gauge",
                 "per-rule health state (0 ok / 1 warn / 2 critical)")
        for name, _desc, st in eng.rule_table():
            p.sample("yacy_health_rule",
                     {"ok": 0, "warn": 1, "critical": 2}[st.state],
                     {"rule": name})
        p.family("yacy_health_incidents_total", "counter",
                 "flight-recorder incident dumps since start")
        p.sample("yacy_health_incidents_total", eng.incident_count)

    # -- actuator layer (ISSUE 9): every closed-loop state change is a
    # counted transition, the current ladder rung is a gauge, and the
    # per-level served-query histogram attributes degradation coverage.
    # Zero-filled per (actuator, dir) so alert expressions always
    # resolve (the no-dead-actuators gate mirrors the rules').
    act = getattr(sb, "actuators", None)
    p.family("yacy_actuator_transitions_total", "counter",
             "actuator state changes by direction along each "
             "actuator's own axis (serving_ladder: down=degrade/"
             "up=recover; batcher_autotune: up=grow pool/down=shrink; "
             "remote_peer_guard: down=peers newly avoided/up=healed); "
             "zero during healthy serving")
    if act is not None:
        for (aname, d), v in sorted(act.transition_counts().items()):
            p.sample("yacy_actuator_transitions_total", v,
                     {"actuator": aname, "dir": d})
    p.family("yacy_degrade_level", "gauge",
             "current degradation-ladder rung this node SERVES under "
             "(0 full .. 4 shed; a rank-service worker reports the "
             "owner-propagated rung it actually applies)")
    p.sample("yacy_degrade_level",
             act.effective_level() if act is not None else 0)
    p.family("yacy_degraded_queries_total", "counter",
             "queries served per degradation-ladder rung")
    for lvl in range(5):
        p.sample("yacy_degraded_queries_total",
                 act.degraded_queries[lvl] if act is not None else 0,
                 {"level": str(lvl)})
    p.family("yacy_shed_requests_total", "counter",
             "requests refused by the ladder's shed rung")
    p.sample("yacy_shed_requests_total",
             act.shed_count if act is not None else 0)
    bt = getattr(ds, "_batcher", None) if ds is not None else None
    tun = bt.tuning() if bt is not None and hasattr(bt, "tuning") \
        else {"dispatchers": 0, "completer_depth": 0}
    p.family("yacy_batcher_tuning", "gauge",
             "live batcher pool geometry (the auto-tuner's actuation "
             "surface)")
    for param in ("dispatchers", "completer_depth"):
        p.sample("yacy_batcher_tuning", tun.get(param, 0),
                 {"param": param})
    # -- streaming-ingest write path (ISSUE 13): crawl-to-searchable
    # doc counts per tier, backpressure waits, and the merge/promotion
    # scheduler's deferral bookkeeping.  Always emitted (the tracker is
    # process-global; scheduler counters zero-fill without one) so the
    # ingest_slo_searchable rule and the merge_scheduler actuator
    # resolve on every node configuration.  The latency tiers
    # themselves ride the ingest.* histogram families above.
    from ...ingest import slo as ingest_slo
    ic = dict(ingest_slo.TRACKER.counters())
    sched = getattr(sb, "ingest_scheduler", None)
    sc = sched.counters() if sched is not None else {}
    p.family("yacy_ingest_total", "counter",
             "write-path counters: docs stamped/searchable/flushed/"
             "device per crawl-to-searchable tier, dropped stamps, "
             "counted backpressure waits, and the merge/promotion "
             "scheduler's deferrals + catch-ups")
    for key in ("docs_stamped", "docs_searchable", "docs_flushed",
                "docs_device", "stamps_dropped", "backpressure_waits"):
        p.sample("yacy_ingest_total", ic.get(key, 0), {"counter": key})
    for key in ("merge_deferrals", "promote_deferrals",
                "merge_catch_ups", "catch_up_merges",
                "catch_up_promotions"):
        p.sample("yacy_ingest_total", sc.get(key, 0), {"counter": key})
    p.family("yacy_ingest_deferred", "gauge",
             "1 while the merge/promotion scheduler is deferring "
             "(serving SLO burning), else 0")
    p.sample("yacy_ingest_deferred", sc.get("deferred", 0))
    p.family("yacy_ingest_deferred_promotions", "gauge",
             "tier promotions currently parked by the deferral")
    p.sample("yacy_ingest_deferred_promotions",
             sc.get("deferred_promotions_parked", 0))
    p.family("yacy_remotesearch_peers_total", "counter",
             "remote-search peer decisions (asked / skipped_sick / "
             "adaptive_timeout) — attributes every fleet-driven skip")
    rc = fl.remote_counter_snapshot() if fl is not None else {}
    for outcome in ("asked", "skipped_sick", "adaptive_timeout"):
        p.sample("yacy_remotesearch_peers_total", rc.get(outcome, 0),
                 {"outcome": outcome})
    return p.text() + ("# EOF\n" if openmetrics else "")


@servlet("metrics")
def respond_metrics(header: dict, post: ServerObjects,
                    sb) -> ServerObjects:
    """GET /metrics — Prometheus text exposition.  Classic 0.0.4 by
    default; an Accept header naming openmetrics-text (what a
    Prometheus server with exemplar support negotiates) or
    `format=openmetrics` upgrades to OpenMetrics WITH the trace-id
    exemplars — which a classic parser would reject, so they never
    appear on the 0.0.4 form."""
    om = ("openmetrics" in header.get("accept", "")
          or post.get("format", "") == "openmetrics")
    prop = ServerObjects()
    prop.raw_body = prometheus_text(sb, openmetrics=om)
    prop.raw_ctype = (
        "application/openmetrics-text; version=1.0.0; charset=utf-8"
        if om else "text/plain; version=0.0.4; charset=utf-8")
    return prop


# -- whitebox profiler dashboard (ISSUE 20) -----------------------------------


def _flame_png(stacks: list, w: int = 800, h: int = 360) -> bytes:
    """Icicle-layout flamegraph over the top folded stacks: row 0 is
    all samples, each deeper row splits a frame's width among its
    children proportionally to sample counts.  Rendered on the raster
    layer like the roofline/waterfall charts."""
    from ...visualization.raster import RasterPlotter

    img = RasterPlotter(w, h, background=(10, 10, 30))
    total = sum(s.get("count", 0) for s in stacks)
    if total <= 0:
        img.text(16, 16, "NO SAMPLES", (200, 200, 200))
        return img.png_bytes()
    row_h = 16
    max_depth = (h - 24) // row_h

    # prefix tree: node = {count, children{frame: node}}
    root = {"count": total, "children": {}}
    for s in stacks:
        node = root
        for frame in s["stack"].split(";")[:max_depth]:
            kids = node["children"]
            if frame not in kids:
                kids[frame] = {"count": 0, "children": {}}
            node = kids[frame]
            node["count"] += s["count"]

    palette = [(205, 92, 52), (224, 138, 56), (198, 66, 66),
               (226, 170, 62), (182, 102, 38)]

    def draw(node, depth, x0, x1):
        if depth >= max_depth or x1 - x0 < 2:
            return
        x = x0
        for i, (frame, child) in enumerate(sorted(
                node["children"].items(),
                key=lambda kv: -kv[1]["count"])):
            width = (x1 - x0) * child["count"] / max(1, node["count"])
            cx1 = min(x1, x + width)
            if cx1 - x >= 2:
                color = palette[(depth + i) % len(palette)]
                y = 20 + depth * row_h
                img.rect(int(x), y, int(cx1) - 1, y + row_h - 2,
                         color, fill=True)
                label = frame.split(":")[-1] if depth else frame
                if (cx1 - x) >= 6 * len(label[:10]) + 4:
                    img.text(int(x) + 2, y + 4, label[:24], (0, 0, 0))
                draw(child, depth + 1, x, cx1)
            x = cx1
    img.text(16, 4, f"PROFILE {total} SAMPLES", (220, 220, 220))
    draw(root, 0, 16, w - 16)
    return img.png_bytes()


@servlet("Performance_Prof_p")
def respond_prof(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Whitebox profiler dashboard (ISSUE 20): top folded stacks with
    role tags, the per-lock wait/hold table with recent over-p95
    holder stacks, and the last triggered deep capture.  `format=json`
    exports the full wire snapshot (what do_profsnap ships);
    `format=png` renders the raster flamegraph."""
    import json as _json

    from ...utils import profiling

    n = post.get_int("n", 12)
    snap = profiling.snapshot(n)
    fmt = post.get("format", "")
    if fmt == "png":
        prop = ServerObjects()
        prop.raw_body = _flame_png(snap["stacks"])
        prop.raw_ctype = "image/png"
        return prop
    if fmt == "json":
        prop = ServerObjects()
        prop.raw_body = _json.dumps(snap, indent=1)
        prop.raw_ctype = "application/json; charset=utf-8"
        return prop
    prop = ServerObjects()
    prop.put("enabled", 1 if snap["enabled"] else 0)
    prop.put("sampler_hz", snap["sampler_hz"])
    prop.put("samples_total", snap["samples_total"])
    prop.put("capture_windows_total", snap["capture_windows_total"])
    prop.put("holder_captures_total", snap["holder_captures_total"])
    prop.put("stacks", len(snap["stacks"]))
    for i, st in enumerate(snap["stacks"]):
        p = f"stacks_{i}_"
        prop.put(p + "role", escape_json(st["role"]))
        prop.put(p + "count", st["count"])
        prop.put(p + "stack", escape_json(st["stack"]))
    for role in profiling.ROLES:
        prop.put(f"role_{role.replace('-', '_')}_samples",
                 snap["roles"].get(role, 0))
    prop.put("locks", len(snap["locks"]))
    for i, row in enumerate(snap["locks"]):
        p = f"locks_{i}_"
        prop.put(p + "name", escape_json(row["name"]))
        prop.put(p + "contended_total", row["contended_total"])
        prop.put(p + "wait_count", row["wait"]["count"])
        prop.put(p + "wait_p50_ms", row["wait"]["p50_ms"])
        prop.put(p + "wait_p95_ms", row["wait"]["p95_ms"])
        prop.put(p + "hold_count", row["hold"]["count"])
        prop.put(p + "hold_p50_ms", row["hold"]["p50_ms"])
        prop.put(p + "hold_p95_ms", row["hold"]["p95_ms"])
        prop.put(p + "holder_stacks", len(row["holder_stacks"]))
        for k, hs in enumerate(row["holder_stacks"]):
            prop.put(f"{p}holder_{k}_hold_ms", hs["hold_ms"])
            prop.put(f"{p}holder_{k}_stack", escape_json(hs["stack"]))
    cap = snap.get("last_capture")
    prop.put("capture", 1 if cap else 0)
    if cap:
        prop.put("capture_reason", escape_json(cap["reason"]))
        prop.put("capture_samples", cap["samples"])
        prop.put("capture_stacks", len(cap["stacks"]))
        for i, st in enumerate(cap["stacks"]):
            p = f"capture_stacks_{i}_"
            prop.put(p + "role", escape_json(st["role"]))
            prop.put(p + "count", st["count"])
            prop.put(p + "stack", escape_json(st["stack"]))
    return prop
