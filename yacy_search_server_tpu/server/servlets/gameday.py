"""Performance_GameDay_p — the game-day verdict table (ISSUE 19).

Performance_Tail_p explains WHY individual queries were slow;
Performance_Health_p shows THAT the SLO is burning.  This panel closes
the loop on the chaos drill itself: for the most recent ``bench.py
--game-day`` run it renders one row per SCHEDULED fault — was it
detected, was the incident attributed to the RIGHT cause label and
member, did the SLO recover inside the bound after the clear, was
every request during the window answered (degraded + counted, never a
5xx), and did the recovered fleet rank bit-identically to the pre-fault
baseline.  The in-process view (:data:`~...utils.gameday.LAST_RUN`)
wins; with no run this process, the newest committed ``CHAOS_r*.json``
artifact at the repo root is served instead, so the panel is useful on
a fresh operator node too.  ``format=json`` exports the full artifact.
"""

from __future__ import annotations

import glob
import json
import os

from ...utils import gameday
from ..objects import ServerObjects, escape_json
from . import servlet

GATES = ("detected", "attributed", "answered", "slo_recovery",
         "bit_identical")


def _newest_artifact() -> str | None:
    """Newest committed ``CHAOS_r*.json`` that actually has a fault
    schedule (every --game-day run commits the next round; pre-M90
    residues without a schedule don't qualify)."""
    here = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(here))))
    for path in sorted(glob.glob(os.path.join(root, "CHAOS_r*.json")),
                       reverse=True):
        try:
            with open(path, encoding="utf-8") as f:
                if json.load(f).get("schedule"):
                    return path
        except (OSError, ValueError):
            continue
    return None


def gameday_view() -> dict:
    """The newest game-day result: this process's LAST_RUN if a run
    happened here, else the newest committed artifact, else an empty
    shell."""
    if gameday.LAST_RUN is not None:
        return {"source": "live", **gameday.LAST_RUN}
    path = _newest_artifact()
    if path is not None:
        try:
            with open(path, encoding="utf-8") as f:
                return {"source": os.path.basename(path),
                        **json.load(f)}
        except (OSError, ValueError):
            pass
    return {"source": "none", "schedule": [], "overlaps": [],
            "verdict_summary": {}, "workload": {}}


@servlet("Performance_GameDay_p")
def respond_gameday(header: dict, post: ServerObjects,
                    sb) -> ServerObjects:
    view = gameday_view()
    if post.get("format", "") == "json":
        prop = ServerObjects()
        prop.raw_body = json.dumps(view, indent=1)
        prop.raw_ctype = "application/json; charset=utf-8"
        return prop
    prop = ServerObjects()
    prop.put("source", escape_json(view.get("source", "none")))
    summary = view.get("verdict_summary", {})
    prop.put("faults", summary.get("faults", 0))
    prop.put("passed", summary.get("passed", 0))
    prop.put("all_pass", 1 if summary.get("all_pass") else 0)
    prop.put("unattributed", summary.get("unattributed_verdicts", 0))
    prop.put("never_500", 1 if summary.get("never_500") else 0)
    wl = view.get("workload", {})
    prop.put("queries_total", wl.get("queries_total", 0))
    prop.put("duration_s", wl.get("duration_s", 0))
    trend = view.get("trend") or {}
    prop.put("trend_prev", escape_json(
        str(trend.get("prev_artifact", "-"))))
    prop.put("trend_regressions", trend.get("regressions", 0))
    prop.put("trend_improvements", trend.get("improvements", 0))

    overlaps = view.get("overlaps", [])
    prop.put("overlaps", len(overlaps))
    for i, pair in enumerate(overlaps):
        prop.put(f"overlaps_{i}_pair", escape_json("+".join(pair)))

    rows = view.get("schedule", [])
    prop.put("rows", len(rows))
    for i, r in enumerate(rows):
        pre = f"rows_{i}_"
        prop.put(pre + "fault_id", escape_json(r.get("fault_id", "")))
        prop.put(pre + "point", escape_json(r.get("point", "")))
        prop.put(pre + "target", escape_json(r.get("target", "")))
        prop.put(pre + "value", escape_json(str(r.get("value", ""))))
        prop.put(pre + "window",
                 escape_json(f"[{r.get('t_arm', 0)}s, "
                             f"{r.get('t_clear', 0)}s]"))
        prop.put(pre + "scenario", escape_json(r.get("scenario", "")))
        for g in GATES:
            prop.put(pre + g, 1 if r.get(g) else 0)
        prop.put(pre + "verdict", escape_json(r.get("verdict", "")))
        rec = r.get("recovery", {}) or {}
        rs = rec.get("recovered_s")
        prop.put(pre + "recovered_s",
                 "-" if rs is None else f"{rs:.1f}")
        ans = r.get("answered_detail", {}) or {}
        prop.put(pre + "answered_detail", escape_json(
            f"{ans.get('ok_200', 0)}x200 "
            f"{ans.get('degraded_429', 0)}x429 "
            f"{ans.get('errors', 0)}xERR"))
    return prop
