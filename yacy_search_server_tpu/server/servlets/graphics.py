"""Graphics + linked-data servlets.

Capability equivalents of the reference's image servlets and vocabulary
admin (reference: htroot/NetworkPicture.java — the DHT ring PNG;
htroot/WebStructurePicture_p.java — host link graph PNG;
htroot/Vocabulary_p.java — vocabulary creation/editing + autotagging
control; htroot/api/ymarks or triple-store surfaces via cora/lod)."""

from __future__ import annotations

from ..objects import ServerObjects, escape_json
from . import servlet


@servlet("NetworkPicture")
def respond_network_picture(header: dict, post: ServerObjects,
                            sb) -> ServerObjects:
    from ...visualization.graphs import network_graph
    from ...visualization.raster import RasterPlotter
    prop = ServerObjects()
    seeddb = getattr(sb, "seeddb", None)
    if seeddb is None:
        # still answer with a real PNG: the .png path fixes the content type
        img = RasterPlotter(480, 480, background=(8, 8, 32))
        img.text(140, 235, "P2P DISABLED", (200, 200, 220))
        prop.raw_body = img.png_bytes()
        prop.raw_ctype = "image/png"
        return prop
    img = network_graph(seeddb, width=post.get_int("width", 480),
                        height=post.get_int("height", 480))
    prop.raw_body = img.png_bytes()
    prop.raw_ctype = "image/png"
    return prop


@servlet("WebStructurePicture_p")
def respond_structure_picture(header: dict, post: ServerObjects,
                              sb) -> ServerObjects:
    from ...visualization.graphs import web_structure_graph
    prop = ServerObjects()
    img = web_structure_graph(
        sb.web_structure, width=post.get_int("width", 640),
        height=post.get_int("height", 480),
        max_hosts=post.get_int("hosts", 24))
    prop.raw_body = img.png_bytes()
    prop.raw_ctype = "image/png"
    return prop


@servlet("AccessPicture_p")
def respond_access_picture(header: dict, post: ServerObjects,
                           sb) -> ServerObjects:
    """Access-grid PNG: who hit this node lately, who it's connected to
    (reference: htroot/AccessPicture_p.java)."""
    from ...visualization.graphs import access_picture
    prop = ServerObjects()
    w = max(32, min(1920, post.get_int("width", 1024)))
    h = max(24, min(1440, post.get_int("height", 576)))
    name = "peer"
    seeddb = getattr(sb, "seeddb", None)
    if seeddb is not None and getattr(seeddb, "my_seed", None) is not None:
        name = seeddb.my_seed.name
    img = access_picture(getattr(sb, "access_tracker", None), name,
                         seeddb=seeddb, width=w, height=h,
                         cellsize=max(6, post.get_int("cellsize", 18)))
    prop.raw_body = img.png_bytes()
    prop.raw_ctype = "image/png"
    return prop


@servlet("PeerLoadPicture")
def respond_peer_load_picture(header: dict, post: ServerObjects,
                              sb) -> ServerObjects:
    """Busy-thread load pie PNG (reference: htroot/PeerLoadPicture.java)."""
    from ...visualization.graphs import peer_load_picture
    prop = ServerObjects()
    w = max(40, min(1920, post.get_int("width", 800)))
    h = max(30, min(1440, post.get_int("height", 600)))
    img = peer_load_picture(getattr(sb, "threads", None), width=w, height=h,
                            showidle=post.get("showidle", "1") != "0")
    prop.raw_body = img.png_bytes()
    prop.raw_ctype = "image/png"
    return prop


@servlet("SearchEventPicture")
def respond_search_event_picture(header: dict, post: ServerObjects,
                                 sb) -> ServerObjects:
    """Per-search-event network PNG: which peers the last (or named)
    search scattered to and which answered (reference:
    htroot/SearchEventPicture.java)."""
    from ...visualization.graphs import search_event_picture
    from ...visualization.raster import RasterPlotter
    prop = ServerObjects()
    cache = getattr(sb, "search_cache", None)
    eid = post.get("event") or (cache.last_event_id if cache else None)
    ev = cache.event_by_id(eid) if (cache and eid) else None
    if ev is None:
        img = RasterPlotter(1, 1, background=(0, 0, 0))   # empty image
    else:
        img = search_event_picture(
            getattr(sb, "seeddb", None), ev,
            width=max(32, min(1920, post.get_int("width", 640))),
            height=max(24, min(1440, post.get_int("height", 480))))
    prop.raw_body = img.png_bytes()
    prop.raw_ctype = "image/png"
    return prop


@servlet("Vocabulary_p")
def respond_vocabulary(header: dict, post: ServerObjects,
                       sb) -> ServerObjects:
    from ...document.vocabulary import Vocabulary
    prop = ServerObjects()
    if post.get("create") and post.get("terms"):
        voc = sb.vocabularies.get(post.get("create")) \
            or Vocabulary(post.get("create"))
        # terms format: tag1:term1,term2;tag2:term3 ...
        for group in post.get("terms").split(";"):
            if ":" not in group:
                continue
            tag, terms = group.split(":", 1)
            voc.put(tag.strip(), terms.split(","))
        sb.vocabularies.put(voc)
    if post.get("test"):
        tags = sb.vocabularies.tag_document(post.get("test"))
        prop.put("matches", len(tags))
        for i, (name, ts) in enumerate(sorted(tags.items())):
            prop.put(f"matches_{i}_vocabulary", escape_json(name))
            prop.put(f"matches_{i}_tags", escape_json(",".join(sorted(ts))))
    names = sb.vocabularies.names()
    prop.put("vocabularies", len(names))
    for i, n in enumerate(names):
        prop.put(f"vocabularies_{i}_name", escape_json(n))
        prop.put(f"vocabularies_{i}_tags", len(sb.vocabularies.get(n).tags()))
    return prop
