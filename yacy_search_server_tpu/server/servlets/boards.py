"""Board + data servlets: wiki, blog, messages, bookmarks, user admin,
table CRUD API, recorded-API table.

Capability equivalents of the reference's community/data servlets
(reference: htroot/Wiki.java, Blog.java, Messages_p.java,
Bookmarks.java, ConfigAccounts_p.java, htroot/api/table_p.java,
Table_API_p.java). JSON-shaped property maps; admin-only where the
reference gates (_p suffix)."""

from __future__ import annotations

import json

from ..objects import ServerObjects, escape_json
from . import servlet


@servlet("Wiki")
def respond_wiki(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    page = post.get("page", "start")
    if post.get("content"):
        sb.wiki.put(page, post.get("content"),
                    author=post.get("author", "anonymous"))
    row = sb.wiki.get(page)
    prop.put("page", escape_json(page))
    prop.put("content", escape_json(row["content"] if row else ""))
    prop.put("html", escape_json(sb.wiki.render(page)))
    prop.put("author", escape_json(row["author"] if row else ""))
    prop.put("pages", escape_json(",".join(sb.wiki.pages())))
    prop.put("versions", len(sb.wiki.history(page)))
    return prop


@servlet("Blog")
def respond_blog(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    if post.get("subject") and post.get("content"):
        sb.blog.add(post.get("subject"), post.get("content"),
                    author=post.get("author", "anonymous"))
    entries = sb.blog.entries(post.get_int("count", 20))
    prop.put("entries", len(entries))
    for i, e in enumerate(entries):
        prop.put(f"entries_{i}_pk", e["_pk"])
        prop.put(f"entries_{i}_subject", escape_json(e.get("subject", "")))
        prop.put(f"entries_{i}_author", escape_json(e.get("author", "")))
        prop.put(f"entries_{i}_date", int(e.get("date", 0)))
        prop.put(f"entries_{i}_html", escape_json(sb.blog.render(e["_pk"])))
        prop.put(f"entries_{i}_comments", len(e.get("comments", [])))
    return prop


@servlet("Messages_p")
def respond_messages(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    action = post.get("action", "list")
    user = post.get("user", "admin")
    if action == "send" and post.get("to"):
        sb.messages.send(post.get("to"), user, post.get("subject", ""),
                         post.get("content", ""))
    elif action == "read" and post.get("pk"):
        sb.messages.mark_read(post.get("pk"))
    elif action == "delete" and post.get("pk"):
        sb.messages.delete(post.get("pk"))
    inbox = sb.messages.inbox(user)
    prop.put("messages", len(inbox))
    for i, m in enumerate(inbox):
        prop.put(f"messages_{i}_pk", m["_pk"])
        prop.put(f"messages_{i}_from", escape_json(m.get("from", "")))
        prop.put(f"messages_{i}_subject", escape_json(m.get("subject", "")))
        prop.put(f"messages_{i}_read", 1 if m.get("read") else 0)
        prop.put(f"messages_{i}_date", int(m.get("date", 0)))
    return prop


@servlet("Bookmarks")
def respond_bookmarks(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    if post.get("add"):
        sb.bookmarks.add(
            post.get("add"), title=post.get("title", ""),
            description=post.get("description", ""),
            tags=post.get("tags", "").split(","),
            public=post.get("public", "") in ("1", "true", "on"))
    if post.get("delete"):
        sb.bookmarks.remove(post.get("delete"))
    tag = post.get("tag", "")
    rows = sb.bookmarks.by_tag(tag) if tag else sb.bookmarks.all()
    prop.put("bookmarks", len(rows))
    for i, b in enumerate(rows):
        prop.put(f"bookmarks_{i}_url", escape_json(b.get("url", "")))
        prop.put(f"bookmarks_{i}_title", escape_json(b.get("title", "")))
        prop.put(f"bookmarks_{i}_tags", escape_json(",".join(b.get("tags", []))))
        prop.put(f"bookmarks_{i}_public", 1 if b.get("public") else 0)
    prop.put("tags", escape_json(",".join(t for t, _ in sb.bookmarks.tags())))
    return prop


@servlet("ConfigAccounts_p")
def respond_accounts(header: dict, post: ServerObjects, sb) -> ServerObjects:
    prop = ServerObjects()
    if post.get("setAdmin") and post.get("adminPassword"):
        # the bin/passwd.sh surface (reference passwd.sh writes the
        # admin credential)
        sb.config.set("adminAccountPassword", post.get("adminPassword"))
        prop.put("passwordset", 1)
    action = post.get("action", "list")
    user = post.get("user", "")
    if action == "create" and user:
        ok = sb.userdb.create(user, post.get("password", ""),
                              rights=post.get("rights", "").split(","))
        prop.put("created", 1 if ok else 0)
    elif action == "delete" and user:
        prop.put("deleted", 1 if sb.userdb.delete(user) else 0)
    elif action == "grant" and user:
        sb.userdb.grant(user, post.get("right", ""))
    elif action == "revoke" and user:
        sb.userdb.revoke(user, post.get("right", ""))
    users = sb.userdb.users()
    prop.put("users", len(users))
    for i, u in enumerate(users):
        prop.put(f"users_{i}_name", escape_json(u.get("name", "")))
        prop.put(f"users_{i}_rights", escape_json(",".join(u.get("rights", []))))
    return prop


@servlet("table_p")
def respond_table(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Generic table CRUD API (reference: htroot/api/table_p.java)."""
    prop = ServerObjects()
    table = post.get("table", "")
    action = post.get("action", "list")
    if not table:
        prop.put("tables", escape_json(",".join(sb.tables.tables())))
        return prop
    if action == "insert":
        try:
            row = json.loads(post.get("row", "{}"))
        except ValueError:
            row = {}
        prop.put("pk", sb.tables.insert(table, row))
    elif action == "update" and post.get("pk"):
        try:
            row = json.loads(post.get("row", "{}"))
        except ValueError:
            row = {}
        prop.put("updated", 1 if sb.tables.update(table, post.get("pk"), row)
                 else 0)
    elif action == "delete" and post.get("pk"):
        prop.put("deleted", 1 if sb.tables.delete(table, post.get("pk"))
                 else 0)
    rows = sb.tables.rows(table)
    prop.put("table", escape_json(table))
    prop.put("count", len(rows))
    for i, r in enumerate(rows[: post.get_int("maxrows", 100)]):
        prop.put(f"rows_{i}_pk", escape_json(str(r.get("_pk", ""))))
        prop.put(f"rows_{i}_row", escape_json(json.dumps(r)))
    return prop


@servlet("Table_API_p")
def respond_api_table(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Recorded API calls + schedule editing (reference:
    htroot/Table_API_p.java over the WorkTables api table)."""
    prop = ServerObjects()
    if post.get("schedule_pk"):
        sb.work_tables.set_schedule(
            post.get("schedule_pk"), post.get_int("repeat_count", 0),
            post.get("repeat_unit", "days"))
    if post.get("clear"):
        sb.work_tables.clear()
        prop.put("cleared", 1)
    calls = sb.work_tables.calls()
    prop.put("calls", len(calls))
    for i, c in enumerate(calls[: post.get_int("maxrows", 100)]):
        prop.put(f"calls_{i}_pk", c["_pk"])
        prop.put(f"calls_{i}_url", escape_json(c.get("url", "")))
        prop.put(f"calls_{i}_type", escape_json(c.get("type", "")))
        prop.put(f"calls_{i}_comment", escape_json(c.get("comment", "")))
        prop.put(f"calls_{i}_exec_count", c.get("exec_count", 0))
        prop.put(f"calls_{i}_repeat_count", c.get("repeat_count", 0))
        prop.put(f"calls_{i}_repeat_unit", escape_json(c.get("repeat_unit", "")))
    return prop


@servlet("AccessTracker_p")
def respond_accesstracker(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Query log view (reference: htroot/AccessTracker_p.java)."""
    prop = ServerObjects()
    latest = sb.access_tracker.latest(post.get_int("count", 50))
    prop.put("queries", len(latest))
    for i, e in enumerate(latest):
        prop.put(f"queries_{i}_query", escape_json(e.query))
        prop.put(f"queries_{i}_time", int(e.timestamp))
        prop.put(f"queries_{i}_results", e.result_count)
        prop.put(f"queries_{i}_ms", round(e.time_ms, 1))
    # host-level access counts (serverAccessTracker surface)
    hosts = sb.access_tracker.access_hosts()[: post.get_int("maxhosts", 25)]
    prop.put("accesshosts", len(hosts))
    for i, (host, n) in enumerate(hosts):
        prop.put(f"accesshosts_{i}_host", escape_json(host))
        prop.put(f"accesshosts_{i}_count", n)
    return prop
