"""Round-4 admin/api surface tail (VERDICT r3 missing #1/#2).

Capability equivalents of the remaining operationally useful reference
pages: ranking config UIs (reference: htroot/RankingSolr_p.java,
htroot/RankingRWI_p.java), RSS crawl loader (htroot/Load_RSS_p.java),
one-click site crawl (htroot/CrawlStartSite.html), generic table browser
(htroot/Tables_p.java), YMarks bookmark manager (htroot/YMarks.java),
image viewer (htroot/ViewImage.java), web-structure watcher
(htroot/WatchWebStructure_p.java), index share upload
(htroot/api/share.java), browsing trail (htroot/api/trail_p.java) and
ynet search relay (htroot/api/ynetSearch.java).

Deliberately SKIPPED reference pages (low value, enumerated so the gap
is a decision, not an omission): CookieMonitorIncoming/Outgoing (cookie
logging UI), Collage (random-image screensaver), Surftips (community
surf suggestions for the retired yacy.net network), WikiHelp, and the
deprecated skins/Steering applets the reference itself hides.
"""

from __future__ import annotations

from ..objects import ServerObjects, escape_html, escape_json
from . import servlet


@servlet("RankingSolr_p")
def ranking_solr(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Field-boost editor — the metadata-side twin of Ranking_p
    (reference: htroot/RankingSolr_p.java boost maps). Boosts persist in
    config as `search.boost.<field>` and feed the post-ranking stage."""
    prop = ServerObjects()
    fields = ("title", "description_txt", "keywords", "text_t", "host_s",
              "url_file_name_s", "author")
    if post.get("save"):
        for f in fields:
            v = post.get(f"boost_{f}", "")
            if v != "":
                try:
                    sb.config.set(f"search.boost.{f}",
                                  str(max(0.0, float(v))))
                except ValueError:
                    pass
        prop.put("saved", 1)
    elif post.get("reset"):
        for f in fields:
            sb.config.set(f"search.boost.{f}", "")
        prop.put("saved", 1)
    defaults = {"title": 5.0, "description_txt": 2.0, "keywords": 2.0,
                "text_t": 1.0, "host_s": 3.0, "url_file_name_s": 2.0,
                "author": 1.0}
    prop.put("fields", len(fields))
    for i, f in enumerate(fields):
        v = sb.config.get(f"search.boost.{f}", "") or defaults[f]
        prop.put(f"fields_{i}_name", f)
        prop.put(f"fields_{i}_value", v)
        prop.put(f"fields_{i}_eol", 1 if i < len(fields) - 1 else 0)
    return prop


@servlet("RankingRWI_p")
def ranking_rwi(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """RWI (pre-)ranking coefficient editor — same store as Ranking_p
    but grouped the way the reference's RankingRWI_p presents them
    (reference: htroot/RankingRWI_p.java over rankingProfile)."""
    from .admin import respond_ranking
    prop = respond_ranking(header, post, sb)
    prop.put("page", "rwi")
    return prop


@servlet("Load_RSS_p")
def load_rss(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Fetch an RSS/Atom feed, list its entries, and optionally index
    them — with the API-table record that makes scheduled re-loads work
    (reference: htroot/Load_RSS_p.java)."""
    prop = ServerObjects()
    url = post.get("url", "").strip()
    prop.put("url", escape_html(url))
    prop.put("items", 0)
    prop.put("indexed", 0)
    if not url:
        return prop
    from ...crawler.request import Request
    from ...document.parser.registry import parse_source
    try:
        resp = sb.loader.load(Request(url=url))
        if resp.status != 200 or not resp.content:
            prop.put("error", f"fetch failed: status {resp.status}")
            return prop
        docs = parse_source(url, resp.mime_type(), resp.content)
    except Exception as e:
        prop.put("error", escape_html(str(e)))
        return prop
    indexed = 0
    if post.get("indexAllItemContent"):
        for d in docs:
            try:
                sb.index.store_document(d)
                indexed += 1
            except Exception:
                pass
        from urllib.parse import quote
        sb.work_tables.record_api_call(
            f"/Load_RSS_p.html?indexAllItemContent=1&url={quote(url)}",
            "Load_RSS_p", f"rss loader for {url}",
            repeat_count=post.get_int("repeat_count", 0),
            repeat_unit=post.get("repeat_unit", "days"))
    prop.put("indexed", indexed)
    prop.put("items", len(docs))
    for i, d in enumerate(docs[:100]):
        prop.put(f"items_{i}_title", escape_html(d.title or d.url))
        prop.put(f"items_{i}_url", escape_html(d.url))
        prop.put(f"items_{i}_eol", 1 if i < min(len(docs), 100) - 1 else 0)
    return prop


@servlet("CrawlStartSite")
def crawl_start_site(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """One-click site crawl: a single URL box that starts a full-site
    crawl bounded to the start host (reference: htroot/CrawlStartSite
    .html posting into Crawler_p with the site filter preset)."""
    prop = ServerObjects()
    url = post.get("crawlingURL", "").strip()
    prop.put("started", 0)
    prop.put("info", "")
    if url and "crawlingstart" in post:
        import re as _re
        from urllib.parse import urlsplit
        host = urlsplit(url if "://" in url else f"http://{url}").hostname
        try:
            profile = sb.start_crawl(
                url if "://" in url else f"http://{url}",
                depth=post.get_int("crawlingDepth", 99),
                crawler_url_must_match=(
                    rf"https?://{_re.escape(host)}/.*" if host else ".*"))
            prop.put("started", 1)
            prop.put("handle", profile.handle)
        except ValueError as e:
            prop.put("info", escape_json(str(e)))
    return prop


@servlet("Tables_p")
def tables(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Generic table browser over the work tables (reference:
    htroot/Tables_p.java; table_p is the JSON api twin)."""
    from .boards import respond_table
    return respond_table(header, post, sb)


@servlet("YMarks")
def ymarks(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """YMarks bookmark manager: folder- and tag-organized bookmarks over
    the same store as Bookmarks (reference: htroot/YMarks.java — its
    separate table family is a storage detail, the capability is
    folders+tags+crawl-start-from-bookmark)."""
    prop = ServerObjects()
    if post.get("add"):
        tags = [t for t in post.get("tags", "").split(",") if t]
        folder = post.get("folder", "/unsorted")
        sb.bookmarks.add(
            post.get("add"), title=post.get("title", ""),
            description=post.get("description", ""),
            tags=tags + [f"folder:{folder}"],
            public=post.get("public", "") in ("1", "true", "on"))
    if post.get("delete"):
        sb.bookmarks.remove(post.get("delete"))
    folder = post.get("folder", "")
    rows = (sb.bookmarks.by_tag(f"folder:{folder}") if folder
            else sb.bookmarks.all())
    folders = sorted({t[len("folder:"):]
                      for t, _n in sb.bookmarks.tags()
                      if t.startswith("folder:")})
    prop.put("folders", len(folders))
    for i, f in enumerate(folders):
        prop.put(f"folders_{i}_name", escape_html(f))
        prop.put(f"folders_{i}_eol", 1 if i < len(folders) - 1 else 0)
    prop.put("marks", len(rows))
    for i, b in enumerate(rows):
        prop.put(f"marks_{i}_url", escape_json(b.get("url", "")))
        prop.put(f"marks_{i}_title", escape_json(b.get("title", "")))
        prop.put(f"marks_{i}_tags", escape_json(",".join(
            t for t in b.get("tags", []) if not t.startswith("folder:"))))
    return prop


@servlet("ViewImage")
def view_image(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Serve an indexed/cached image (image-search result thumbnails,
    favicon display — reference: htroot/ViewImage.java; the reference's
    server-side rescale is skipped: clients scale, the bytes are what
    the cache holds). Cache-only by default; the live fetch obeys the
    SSRF guard."""
    prop = ServerObjects()
    url = post.get("url", "")
    if not url:
        prop.put("error", "missing url")
        return prop
    got = sb.htcache.get(url)
    content, ctype = None, "image/png"
    if got is not None:
        content = got[0]
        ctype = got[1].get("content-type", "image/png")
    else:
        from ..netguard import refuse_addr, unsafe_target
        allow_private = bool(header.get("admin"))
        if unsafe_target(url, sb.loader, allow_private=allow_private):
            prop.put("error", "target refused")
            return prop
        from ...crawler.request import Request
        try:
            # the guard rides every redirect hop AND pins the
            # connection to the vetted resolution (netguard)
            resp = sb.loader.load(
                Request(url=url),
                url_filter=lambda u: not unsafe_target(
                    u, sb.loader, allow_private=allow_private),
                addr_guard=(None if sb.loader.transport is not None else
                            (lambda a: refuse_addr(a, allow_private))))
            if resp.status == 200 and resp.content:
                content = resp.content
                ctype = resp.headers.get("content-type", "image/png")
        except Exception:
            pass
    if content is None:
        prop.put("error", "not available")
        return prop
    if not ctype.lower().startswith("image/"):
        prop.put("error", "not an image")
        return prop
    prop.raw_body = content
    prop.raw_ctype = ctype
    return prop


@servlet("WatchWebStructure_p")
def watch_web_structure(header: dict, post: ServerObjects,
                        sb) -> ServerObjects:
    """Web-structure watcher: host-centered link graph with depth/width
    knobs, rendered by WebStructurePicture_p (reference:
    htroot/WatchWebStructure_p.java)."""
    prop = ServerObjects()
    host = post.get("host", "auto")
    if host == "auto":
        hosts = sb.web_structure.top_hosts(200)
        host = hosts[0][0] if hosts else ""
    prop.put("host", escape_html(host))
    prop.put("depth", post.get_int("depth", 2))
    prop.put("width", post.get_int("width", 1024))
    prop.put("height", post.get_int("height", 576))
    # the known host list feeds the page's datalist
    known = sb.web_structure.top_hosts(200)[:50]
    prop.put("hosts", len(known))
    for i, (h, refs) in enumerate(known):
        prop.put(f"hosts_{i}_name", escape_html(h))
        prop.put(f"hosts_{i}_refs", refs)
        prop.put(f"hosts_{i}_eol", 1 if i < len(known) - 1 else 0)
    return prop


@servlet("share")
def share(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Surrogate upload: push an indexable dump to this peer; it lands
    in the surrogate-in directory and the indexer imports it
    (reference: htroot/api/share.java storing into yacy.getDataPath +
    surrogates/in). Content rides the `data` field (the form-encoded
    transport this server speaks; multipart is a transport detail)."""
    prop = ServerObjects()
    name = post.get("name", "upload.xml")
    data = post.get("data", "")
    if not data:
        prop.put("mode", 0)
        return prop
    import os
    import re as _re
    safe = _re.sub(r"[^A-Za-z0-9._-]", "_", name)[:128] or "upload.xml"
    outdir = sb.surrogates_in
    os.makedirs(outdir, exist_ok=True)
    path = os.path.join(outdir, safe)
    with open(path, "w", encoding="utf-8") as f:
        f.write(data)
    prop.put("mode", 1)
    prop.put("file", escape_html(safe))
    return prop


@servlet("trail_p")
def trail(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Recently searched/viewed items of this node's UI session
    (reference: htroot/api/trail_p.java over Switchboard.trail)."""
    prop = ServerObjects()
    items = list(getattr(sb, "trail", ()))
    prop.put("trails", len(items))
    for i, t in enumerate(items):
        prop.put(f"trails_{i}_trail", escape_json(t))
    return prop


@servlet("ynetSearch")
def ynet_search(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Search relay: fetch a (possibly remote) search address with the
    remaining query parameters appended and return the raw body
    (reference: htroot/api/ynetSearch.java). Admin-gated by default
    (security.DEFAULT_ADMIN_PATHS — the reference relays blindly; an
    open relay is a deliberate divergence), and the target/redirect
    chain still passes the SSRF predicate."""
    prop = ServerObjects()
    url = post.get("url", "")
    if not url:
        prop.put("url", "error!")
        return prop
    if not url.startswith(("http://", "https://")):
        host = header.get("host", "localhost")
        url = f"http://{host}" + ("" if url.startswith("/") else "/") + url
    from ..netguard import unsafe_target
    if unsafe_target(url, sb.loader,
                     allow_private=bool(header.get("admin"))):
        prop.put("url", "error!")
        return prop
    params = "&".join(f"{k}={v}" for k, v in post.items()
                      if k not in ("url", "login"))
    target = url + ("&" if "?" in url else "?") + params if params else url
    from ...crawler.request import Request
    try:
        resp = sb.loader.load(
            Request(url=target),
            url_filter=lambda u: not unsafe_target(
                u, sb.loader,
                allow_private=bool(header.get("admin"))))
        prop.put("http", resp.content.decode("utf-8", "replace")
                 if resp.content else "")
    except Exception:
        prop.put("url", "error!")
    return prop
