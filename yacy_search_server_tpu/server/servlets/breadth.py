"""Round-4 admin/api surface tail (VERDICT r3 missing #1/#2).

Capability equivalents of the remaining operationally useful reference
pages: ranking config UIs (reference: htroot/RankingSolr_p.java,
htroot/RankingRWI_p.java), RSS crawl loader (htroot/Load_RSS_p.java),
one-click site crawl (htroot/CrawlStartSite.html), generic table browser
(htroot/Tables_p.java), YMarks bookmark manager (htroot/YMarks.java),
image viewer (htroot/ViewImage.java), web-structure watcher
(htroot/WatchWebStructure_p.java), index share upload
(htroot/api/share.java), browsing trail (htroot/api/trail_p.java) and
ynet search relay (htroot/api/ynetSearch.java).

Deliberately SKIPPED reference pages (enumerated so every gap is a
decision, not an omission — audited against the full htroot listing):
- privacy/abandoned: CookieMonitorIncoming_p/CookieMonitorOutgoing_p + CookieTest_p
  (cookie logging), Collage (random-image screensaver), Surftips +
  Supporter + compare_yacy + TransNews_p (retired yacy.net community
  services), WikiHelp, YaCySearchPluginFF (autoconfig covers it),
  jslicense, test/imagetest/ssitest/ssitestservlet (dev scaffolding)
- needs external egress or site-specific scraping: osm (tile proxy),
  DictionaryLoader_p (downloads dictionaries; geo data ships bundled),
  Load_MediawikiWiki / Load_PHPBB3 / ContentIntegrationPHPBB3_p
  (site-specific import wizards; WARC/MediaWiki/OAI importers cover
  the capability), rct_p (remote crawl trigger UI; RemoteCrawl_p
  covers the capability)
- LAN scanning: CrawlStartScanner_p / ServerScannerList (a network
  scanner is out of scope for a search node's default surface)
- graphics variants: cytag (a per-peer event-dot tag image for the
  retired yacy.net homepage; NetworkPicture, PerformanceGraph,
  WebStructurePicture_p, Banner, AccessPicture_p, PeerLoadPicture and
  SearchEventPicture cover the raster surface — the last three live,
  round 5)
- thin redirect/ack shells the SPA-less UI does not need: goto_p,
  SettingsAck_p, CrawlMonitorRemoteStart, HostBrowserAdmin_p
  (HostBrowser serves both), BlogComments (Blog covers it),
  CacheResource_p (ViewFile?viewMode=raw serves cached content),
  Table_RobotsTxt_p (robots rules render in ConfigRobotsTxt_p),
  IndexImportOAIPMHList_p (IndexImportOAIPMH_p covers it),
  IndexFederated_p (no external Solr federation by design — the
  columnar store replaces it), ConfigParser_p (every parser ships
  enabled; the registry is not runtime-toggleable by design),
  ConfigSearchBox (ConfigPortal_p/ConfigSearchPage_p cover it),
  ContentAnalysis_p (signature thresholds are code constants),
  Trails (trail_p serves the data), mediawiki_p (export),
  yacysearchlatestinfo / yacysearchpagination (the served page +
  yacysearchitem/yacysearchtrailer fragments cover progressive
  delivery), rssTerminal / terminal_p (retired visualizations),
  Steering (Steering_p serves it), User (User_p serves it).
"""

from __future__ import annotations

from ..objects import ServerObjects, escape_html, escape_json
from . import servlet


@servlet("RankingSolr_p")
def ranking_solr(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Field-boost editor — the metadata-side twin of Ranking_p
    (reference: htroot/RankingSolr_p.java boost maps). Boosts persist in
    config as `search.boost.<field>` and feed the post-ranking stage."""
    prop = ServerObjects()
    fields = ("title", "description_txt", "keywords", "text_t", "host_s",
              "url_file_name_s", "author")
    if post.get("save"):
        for f in fields:
            v = post.get(f"boost_{f}", "")
            if v != "":
                try:
                    sb.config.set(f"search.boost.{f}",
                                  str(max(0.0, float(v))))
                except ValueError:
                    pass
        prop.put("saved", 1)
    elif post.get("reset"):
        for f in fields:
            sb.config.set(f"search.boost.{f}", "")
        prop.put("saved", 1)
    defaults = {"title": 5.0, "description_txt": 2.0, "keywords": 2.0,
                "text_t": 1.0, "host_s": 3.0, "url_file_name_s": 2.0,
                "author": 1.0}
    prop.put("fields", len(fields))
    for i, f in enumerate(fields):
        v = sb.config.get(f"search.boost.{f}", "") or defaults[f]
        prop.put(f"fields_{i}_name", f)
        prop.put(f"fields_{i}_value", v)
        prop.put(f"fields_{i}_eol", 1 if i < len(fields) - 1 else 0)
    return prop


@servlet("RankingRWI_p")
def ranking_rwi(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """RWI (pre-)ranking coefficient editor — same store as Ranking_p
    but grouped the way the reference's RankingRWI_p presents them
    (reference: htroot/RankingRWI_p.java over rankingProfile)."""
    from .admin import respond_ranking
    prop = respond_ranking(header, post, sb)
    prop.put("page", "rwi")
    return prop


@servlet("Load_RSS_p")
def load_rss(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Fetch an RSS/Atom feed, list its entries, and optionally index
    them — with the API-table record that makes scheduled re-loads work
    (reference: htroot/Load_RSS_p.java)."""
    prop = ServerObjects()
    url = post.get("url", "").strip()
    prop.put("url", escape_html(url))
    prop.put("items", 0)
    prop.put("indexed", 0)
    if not url:
        return prop
    from ...crawler.request import Request
    from ...document.parser.registry import parse_source
    try:
        resp = sb.loader.load(Request(url=url))
        if resp.status != 200 or not resp.content:
            prop.put("error", f"fetch failed: status {resp.status}")
            return prop
        docs = parse_source(url, resp.mime_type(), resp.content)
    except Exception as e:
        prop.put("error", escape_html(str(e)))
        return prop
    indexed = 0
    if post.get("indexAllItemContent"):
        for d in docs:
            try:
                sb.index.store_document(d)
                indexed += 1
            except Exception:
                import logging
                logging.getLogger("servlets.rss").warning(
                    "RSS item not indexed: %s", getattr(d, "url", "?"),
                    exc_info=True)
        from urllib.parse import quote
        sb.work_tables.record_api_call(
            f"/Load_RSS_p.html?indexAllItemContent=1&url={quote(url)}",
            "Load_RSS_p", f"rss loader for {url}",
            repeat_count=post.get_int("repeat_count", 0),
            repeat_unit=post.get("repeat_unit", "days"))
    prop.put("indexed", indexed)
    prop.put("items", len(docs))
    for i, d in enumerate(docs[:100]):
        prop.put(f"items_{i}_title", escape_html(d.title or d.url))
        prop.put(f"items_{i}_url", escape_html(d.url))
        prop.put(f"items_{i}_eol", 1 if i < min(len(docs), 100) - 1 else 0)
    return prop


@servlet("CrawlStartSite")
def crawl_start_site(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """One-click site crawl: a single URL box that starts a full-site
    crawl bounded to the start host (reference: htroot/CrawlStartSite
    .html posting into Crawler_p with the site filter preset)."""
    prop = ServerObjects()
    url = post.get("crawlingURL", "").strip()
    prop.put("started", 0)
    prop.put("info", "")
    if url and "crawlingstart" in post:
        import re as _re
        from urllib.parse import urlsplit
        host = urlsplit(url if "://" in url else f"http://{url}").hostname
        try:
            profile = sb.start_crawl(
                url if "://" in url else f"http://{url}",
                depth=post.get_int("crawlingDepth", 99),
                crawler_url_must_match=(
                    rf"https?://{_re.escape(host)}/.*" if host else ".*"))
            prop.put("started", 1)
            prop.put("handle", profile.handle)
        except ValueError as e:
            prop.put("info", escape_json(str(e)))
    return prop


@servlet("Tables_p")
def tables(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Generic table browser over the work tables (reference:
    htroot/Tables_p.java; table_p is the JSON api twin)."""
    from .boards import respond_table
    return respond_table(header, post, sb)


@servlet("YMarks")
def ymarks(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """YMarks bookmark manager: folder- and tag-organized bookmarks over
    the same store as Bookmarks (reference: htroot/YMarks.java — its
    separate table family is a storage detail, the capability is
    folders+tags+crawl-start-from-bookmark)."""
    prop = ServerObjects()
    if post.get("add"):
        tags = [t for t in post.get("tags", "").split(",") if t]
        folder = post.get("folder", "/unsorted")
        sb.bookmarks.add(
            post.get("add"), title=post.get("title", ""),
            description=post.get("description", ""),
            tags=tags + [f"folder:{folder}"],
            public=post.get("public", "") in ("1", "true", "on"))
    if post.get("delete"):
        sb.bookmarks.remove(post.get("delete"))
    folder = post.get("folder", "")
    rows = (sb.bookmarks.by_tag(f"folder:{folder}") if folder
            else sb.bookmarks.all())
    folders = sorted({t[len("folder:"):]
                      for t, _n in sb.bookmarks.tags()
                      if t.startswith("folder:")})
    prop.put("folders", len(folders))
    for i, f in enumerate(folders):
        prop.put(f"folders_{i}_name", escape_html(f))
        prop.put(f"folders_{i}_eol", 1 if i < len(folders) - 1 else 0)
    prop.put("marks", len(rows))
    for i, b in enumerate(rows):
        prop.put(f"marks_{i}_url", escape_json(b.get("url", "")))
        prop.put(f"marks_{i}_title", escape_json(b.get("title", "")))
        prop.put(f"marks_{i}_tags", escape_json(",".join(
            t for t in b.get("tags", []) if not t.startswith("folder:"))))
    return prop


@servlet("ViewImage")
def view_image(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Serve an indexed/cached image (image-search result thumbnails,
    favicon display — reference: htroot/ViewImage.java; the reference's
    server-side rescale is skipped: clients scale, the bytes are what
    the cache holds). Cache-only by default; the live fetch obeys the
    SSRF guard."""
    prop = ServerObjects()
    url = post.get("url", "")
    if not url:
        prop.put("error", "missing url")
        return prop
    got = sb.htcache.get(url)
    content, ctype = None, "image/png"
    if got is not None:
        content = got[0]
        ctype = got[1].get("content-type", "image/png")
    else:
        from ..netguard import refuse_addr, unsafe_target
        allow_private = bool(header.get("admin"))
        if unsafe_target(url, sb.loader, allow_private=allow_private):
            prop.put("error", "target refused")
            return prop
        from ...crawler.request import Request
        try:
            # the guard rides every redirect hop AND pins the
            # connection to the vetted resolution (netguard)
            resp = sb.loader.load(
                Request(url=url),
                url_filter=lambda u: not unsafe_target(
                    u, sb.loader, allow_private=allow_private),
                addr_guard=(None if sb.loader.transport is not None else
                            (lambda a: refuse_addr(a, allow_private))))
            if resp.status == 200 and resp.content:
                content = resp.content
                ctype = resp.headers.get("content-type", "image/png")
        except Exception:
            import logging
            logging.getLogger("servlets.image").debug(
                "remote image fetch failed for %s", u, exc_info=True)
    if content is None:
        prop.put("error", "not available")
        return prop
    if not ctype.lower().startswith("image/"):
        prop.put("error", "not an image")
        return prop
    prop.raw_body = content
    prop.raw_ctype = ctype
    return prop


@servlet("WatchWebStructure_p")
def watch_web_structure(header: dict, post: ServerObjects,
                        sb) -> ServerObjects:
    """Web-structure watcher: host-centered link graph with depth/width
    knobs, rendered by WebStructurePicture_p (reference:
    htroot/WatchWebStructure_p.java)."""
    prop = ServerObjects()
    host = post.get("host", "auto")
    if host == "auto":
        hosts = sb.web_structure.top_hosts(200)
        host = hosts[0][0] if hosts else ""
    prop.put("host", escape_html(host))
    prop.put("depth", post.get_int("depth", 2))
    prop.put("width", post.get_int("width", 1024))
    prop.put("height", post.get_int("height", 576))
    # the known host list feeds the page's datalist
    known = sb.web_structure.top_hosts(200)[:50]
    prop.put("hosts", len(known))
    for i, (h, refs) in enumerate(known):
        prop.put(f"hosts_{i}_name", escape_html(h))
        prop.put(f"hosts_{i}_refs", refs)
        prop.put(f"hosts_{i}_eol", 1 if i < len(known) - 1 else 0)
    return prop


@servlet("share")
def share(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Surrogate upload: push an indexable dump to this peer; it lands
    in the surrogate-in directory and the indexer imports it
    (reference: htroot/api/share.java storing into yacy.getDataPath +
    surrogates/in). Content rides the `data` field (the form-encoded
    transport this server speaks; multipart is a transport detail)."""
    prop = ServerObjects()
    name = post.get("name", "upload.xml")
    data = post.get("data", "")
    if not data:
        prop.put("mode", 0)
        return prop
    import os
    import re as _re
    safe = _re.sub(r"[^A-Za-z0-9._-]", "_", name)[:128] or "upload.xml"
    outdir = sb.surrogates_in
    os.makedirs(outdir, exist_ok=True)
    path = os.path.join(outdir, safe)
    with open(path, "w", encoding="utf-8") as f:
        f.write(data)
    prop.put("mode", 1)
    prop.put("file", escape_html(safe))
    return prop


@servlet("trail_p")
def trail(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Recently searched/viewed items of this node's UI session
    (reference: htroot/api/trail_p.java over Switchboard.trail)."""
    prop = ServerObjects()
    items = list(getattr(sb, "trail", ()))
    prop.put("trails", len(items))
    for i, t in enumerate(items):
        prop.put(f"trails_{i}_trail", escape_json(t))
    return prop


@servlet("ynetSearch")
def ynet_search(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Search relay: fetch a (possibly remote) search address with the
    remaining query parameters appended and return the raw body
    (reference: htroot/api/ynetSearch.java). Admin-gated by default
    (security.DEFAULT_ADMIN_PATHS — the reference relays blindly; an
    open relay is a deliberate divergence), and the target/redirect
    chain still passes the SSRF predicate."""
    prop = ServerObjects()
    url = post.get("url", "")
    if not url:
        prop.put("url", "error!")
        return prop
    if not url.startswith(("http://", "https://")):
        host = header.get("host", "localhost")
        url = f"http://{host}" + ("" if url.startswith("/") else "/") + url
    from ..netguard import unsafe_target
    if unsafe_target(url, sb.loader,
                     allow_private=bool(header.get("admin"))):
        prop.put("url", "error!")
        return prop
    params = "&".join(f"{k}={v}" for k, v in post.items()
                      if k not in ("url", "login"))
    target = url + ("&" if "?" in url else "?") + params if params else url
    from ...crawler.request import Request
    try:
        resp = sb.loader.load(
            Request(url=target),
            url_filter=lambda u: not unsafe_target(
                u, sb.loader,
                allow_private=bool(header.get("admin"))))
        prop.put("http", resp.content.decode("utf-8", "replace")
                 if resp.content else "")
    except Exception:
        prop.put("url", "error!")
    return prop


# -- round-4 second sweep: crawler monitors, blacklist maintenance, ----------
#    account views, fragments, graphics (closing the audited page gap)


@servlet("ConfigAccountList_p")
def config_account_list(header, post, sb) -> ServerObjects:
    """Read-only account listing (reference: htroot/ConfigAccountList_p
    .java); ConfigAccounts_p is the mutating twin."""
    prop = ServerObjects()
    users = sb.userdb.users()
    prop.put("users", len(users))
    for i, u in enumerate(users):
        prop.put(f"users_{i}_name", escape_html(u.get("name", "")))
        prop.put(f"users_{i}_rights",
                 escape_html(",".join(u.get("rights", []))))
        prop.put(f"users_{i}_eol", 1 if i < len(users) - 1 else 0)
    return prop


@servlet("ConfigUser_p")
def config_user(header, post, sb) -> ServerObjects:
    """Single-user editor (reference: htroot/ConfigUser_p.java) — the
    same store actions as ConfigAccounts_p, focused on one account."""
    from .boards import respond_accounts
    prop = respond_accounts(header, post, sb)
    user = post.get("user", "")
    prop.put("user", escape_html(user))
    for u in sb.userdb.users():
        if u.get("name") == user:
            prop.put("rights", escape_html(",".join(u.get("rights", []))))
    return prop


@servlet("BlacklistImpExp_p")
def blacklist_impexp(header, post, sb) -> ServerObjects:
    """Blacklist import/export as plain pattern-per-line text
    (reference: htroot/BlacklistImpExp_p.java)."""
    prop = ServerObjects()
    name = post.get("list", "default")
    if post.get("import"):
        added = 0
        for line in post.get("import", "").splitlines():
            line = line.strip()
            if line and not line.startswith("#"):
                sb.blacklist.add(name, line)
                added += 1
        prop.put("imported", added)
    entries = sb.blacklist.entries(name) \
        if name in sb.blacklist.list_names() else []
    prop.put("list", escape_html(name))
    prop.put("export", escape_html("\n".join(entries)))
    prop.put("count", len(entries))
    return prop


@servlet("BlacklistCleaner_p")
def blacklist_cleaner(header, post, sb) -> ServerObjects:
    """Drop syntactically broken blacklist entries (reference:
    htroot/BlacklistCleaner_p.java checks every pattern)."""
    import re as _re

    from ...data.blacklist import _host_pattern_to_regex
    prop = ServerObjects()
    removed = []
    for name in sb.blacklist.list_names():
        for pattern in list(sb.blacklist.entries(name)):
            host, _, path = pattern.partition("/")
            try:
                _host_pattern_to_regex(host)
                _re.compile(path or ".*")
            except _re.error:
                if post.get("delete"):
                    sb.blacklist.remove(name, pattern)
                removed.append(f"{name}: {pattern}")
    prop.put("invalid", len(removed))
    for i, p in enumerate(removed[:100]):
        prop.put(f"invalid_{i}_entry", escape_html(p))
        prop.put(f"invalid_{i}_eol",
                 1 if i < min(len(removed), 100) - 1 else 0)
    prop.put("deleted", 1 if post.get("delete") else 0)
    return prop


@servlet("sharedBlacklist_p")
def shared_blacklist(header, post, sb) -> ServerObjects:
    """Import a blacklist published by another peer (reference:
    htroot/sharedBlacklist_p.java fetches a peer's list url)."""
    prop = ServerObjects()
    url = post.get("url", "").strip()
    prop.put("imported", 0)
    if not url:
        return prop
    from ..netguard import unsafe_target
    if unsafe_target(url, sb.loader, allow_private=True):
        prop.put("error", "target refused")
        return prop
    from ...crawler.request import Request
    try:
        resp = sb.loader.load(Request(url=url))
        if resp.status != 200:
            prop.put("error", f"fetch failed: {resp.status}")
            return prop
        name = post.get("list", "shared")
        added = 0
        for line in resp.content.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if line and not line.startswith("#"):
                sb.blacklist.add(name, line)
                added += 1
        prop.put("imported", added)
        prop.put("list", escape_html(name))
    except Exception as e:
        prop.put("error", escape_html(str(e)))
    return prop


@servlet("IndexCreateQueues_p")
def index_create_queues(header, post, sb) -> ServerObjects:
    """Crawler queue monitor: per-stack frontier sizes + a preview of
    pending urls (reference: htroot/IndexCreateQueues_p.java)."""
    from ...crawler.frontier import StackType
    prop = ServerObjects()
    stacks = (StackType.LOCAL, StackType.GLOBAL, StackType.REMOTE,
              StackType.NOLOAD)
    prop.put("stacks", len(stacks))
    for i, st in enumerate(stacks):
        prop.put(f"stacks_{i}_name", st)
        prop.put(f"stacks_{i}_size", sb.noticed.size(st))
        prop.put(f"stacks_{i}_eol", 1 if i < len(stacks) - 1 else 0)
    if post.get("clear"):
        dropped = sum(sb.noticed.clear(st) for st in stacks)
        prop.put("cleared", dropped)
    return prop


@servlet("IndexCreateLoaderQueue_p")
def index_create_loader_queue(header, post, sb) -> ServerObjects:
    """URLs currently being fetched (reference:
    htroot/IndexCreateLoaderQueue_p.java over the loader pool)."""
    prop = ServerObjects()
    with sb.loader._lock:
        inflight = list(sb.loader._inflight)
    prop.put("loads", len(inflight))
    for i, u in enumerate(inflight[:100]):
        prop.put(f"loads_{i}_url", escape_html(u))
        prop.put(f"loads_{i}_eol",
                 1 if i < min(len(inflight), 100) - 1 else 0)
    return prop


@servlet("IndexCreateParserErrors_p")
def index_create_parser_errors(header, post, sb) -> ServerObjects:
    """Recent fetch/parse failures with reasons (reference:
    htroot/IndexCreateParserErrors_p.java over the ErrorCache)."""
    prop = ServerObjects()
    rows = sb.crawl_queues.error_cache.recent(100)
    prop.put("errors", len(rows))
    for i, (url, reason, _ts) in enumerate(rows):
        prop.put(f"errors_{i}_url", escape_html(url))
        prop.put(f"errors_{i}_reason", escape_html(reason))
        prop.put(f"errors_{i}_eol", 1 if i < len(rows) - 1 else 0)
    return prop


@servlet("IndexReIndexMonitor_p")
def index_reindex_monitor(header, post, sb) -> ServerObjects:
    """Postprocessing/reindex status: docs still tagged for a
    postprocessing pass, with a run-now action (reference:
    htroot/IndexReIndexMonitor_p.java)."""
    prop = ServerObjects()
    if post.get("run"):
        prop.put("updated", sb.run_postprocessing())
    meta = sb.index.metadata
    docids = [d for d in range(meta.capacity())
              if not meta.is_deleted(d)]
    # one batched per-segment column read, not capacity() row lookups
    pending = sum(1 for v in meta.text_values(docids, "process_sxt")
                  if v)
    prop.put("pending", pending)
    prop.put("doccount", sb.index.doc_count())
    return prop


@servlet("ProxyIndexingMonitor_p")
def proxy_indexing_monitor(header, post, sb) -> ServerObjects:
    """Proxy-indexing toggles (reference:
    htroot/ProxyIndexingMonitor_p.java): pages fetched through the
    forward proxy feed the indexer when enabled."""
    prop = ServerObjects()
    if post.get("set"):
        sb.config.set("proxyURL",
                      "true" if post.get("proxyURL") else "false")
        sb.config.set("proxyIndexing",
                      "true" if post.get("proxyIndexing") else "false")
        prop.put("saved", 1)
    prop.put("proxyURL", 1 if sb.config.get_bool("proxyURL", False) else 0)
    prop.put("proxyIndexing",
             1 if sb.config.get_bool("proxyIndexing", False) else 0)
    return prop


@servlet("QuickCrawlLink_p")
def quick_crawl_link(header, post, sb) -> ServerObjects:
    """Bookmarklet crawl: index ONE url now (reference:
    htroot/QuickCrawlLink_p.java — the browser-toolbar entry)."""
    prop = ServerObjects()
    url = post.get("url", "").strip()
    host = header.get("host", "localhost")
    prop.put("bookmarklet", escape_html(
        f"javascript:location.href='http://{host}/QuickCrawlLink_p.html"
        f"?url='+escape(location.href)"))
    prop.put("started", 0)
    if url:
        try:
            profile = sb.start_crawl(url, depth=0, name=f"quick {url}")
            prop.put("started", 1)
            prop.put("handle", profile.handle)
        except ValueError as e:
            prop.put("info", escape_json(str(e)))
    return prop


@servlet("MessageSend_p")
def message_send(header, post, sb) -> ServerObjects:
    """Send a P2P message to a peer (reference: htroot/MessageSend_p
    .java; Messages_p is the inbox)."""
    prop = ServerObjects()
    prop.put("sent", 0)
    target_name = post.get("peer", "")
    node = getattr(sb, "node", None)
    seeddb = getattr(sb, "seeddb", None) or getattr(node, "seeddb", None)
    if post.get("send") and target_name and seeddb is not None \
            and node is not None:
        for s in seeddb.all_seeds():
            if s.name == target_name:
                ok = node.protocol.message(
                    s, post.get("subject", ""), post.get("message", ""))
                prop.put("sent", 1 if ok else 0)
                break
    peers = [s.name for s in seeddb.all_seeds()] if seeddb else []
    prop.put("peers", len(peers))
    for i, n in enumerate(peers[:100]):
        prop.put(f"peers_{i}_name", escape_html(n))
        prop.put(f"peers_{i}_eol",
                 1 if i < min(len(peers), 100) - 1 else 0)
    return prop


@servlet("ViewFavicon")
def view_favicon(header, post, sb) -> ServerObjects:
    """Serve an indexed page's favicon (reference: htroot/ViewFavicon
    .java) — resolves the icon url from the document's icon columns and
    rides ViewImage's guarded fetch."""
    from ...index.metadata import split_multi_positional
    from ...utils.hashes import url2hash
    url = post.get("url", "")
    docid = sb.index.metadata.docid(url2hash(url)) if url else None
    if docid is not None:
        meta = sb.index.metadata
        stubs = split_multi_positional(
            meta.text_value(docid, "icons_urlstub_sxt"))
        protos = split_multi_positional(
            meta.text_value(docid, "icons_protocol_sxt"))
        if stubs and stubs[0]:
            # urlstub columns strip the scheme; rebuild it like the
            # image-result path does (searchevent image branch)
            proto = protos[0] if protos and protos[0] else "http"
            post.put("url", f"{proto}://{stubs[0]}")
    return view_image(header, post, sb)


@servlet("yacysearch_location")
def yacysearch_location(header, post, sb) -> ServerObjects:
    """Geo search API: results carrying coordinates, for map UIs
    (reference: htroot/yacysearch_location.java producing kml)."""
    prop = ServerObjects()
    query = post.get("query", "").strip()
    count = min(post.get_int("maximumRecords", 20), 100)
    prop.put("places", 0)
    if not query:
        return prop
    ev = sb.search(query, count=count)
    places = []
    meta = sb.index.metadata
    for e in ev.results(count=count):
        row = meta.row(e.docid) if e.docid >= 0 else None
        if row is None:
            continue               # deleted between ranking and read
        lat, lon = row.get("lat_d"), row.get("lon_d")
        if lat or lon:
            places.append((e.title or e.url, e.url, lat, lon))
    prop.put("places", len(places))
    for i, (name, url, lat, lon) in enumerate(places):
        prop.put(f"places_{i}_name", escape_json(name))
        prop.put(f"places_{i}_url", escape_json(url))
        prop.put(f"places_{i}_lat", lat)
        prop.put(f"places_{i}_lon", lon)
    return prop


@servlet("yacysearchtrailer")
def yacysearch_trailer(header, post, sb) -> ServerObjects:
    """Navigator/facet fragment of a cached search event — the page
    pulls it after the items (reference: htroot/yacysearchtrailer.java
    renders the sidebar from SearchEventCache)."""
    prop = ServerObjects()
    eid = post.get("eventID", "")
    ev = sb.search_cache.event_by_id(eid) if eid else None
    prop.put("navs", 0)
    if ev is None:
        return prop
    navs = [(n, nav) for n, nav in ev.navigators.items()
            if len(nav.counts)]
    prop.put("navs", len(navs))
    for i, (name, nav) in enumerate(navs):
        prop.put(f"navs_{i}_name", escape_html(name))
        top = nav.counts.top(10)
        prop.put(f"navs_{i}_items", len(top))
        for j, (val, cnt) in enumerate(top):
            prop.put(f"navs_{i}_items_{j}_value", escape_html(str(val)))
            prop.put(f"navs_{i}_items_{j}_count", cnt)
    return prop


@servlet("autoconfig")
def autoconfig(header, post, sb) -> ServerObjects:
    """Browser search-plugin autoconfig XML (reference:
    htroot/autoconfig.java / YaCySearchPluginFF)."""
    host = header.get("host", "localhost:8090")
    prop = ServerObjects()
    prop.raw_ctype = "application/opensearchdescription+xml"
    name = sb.config.get("peerName", "yacy-tpu")
    prop.raw_body = (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<OpenSearchDescription '
        'xmlns="http://a9.com/-/spec/opensearch/1.1/">\n'
        f"  <ShortName>YaCy-TPU {escape_html(name)}</ShortName>\n"
        "  <Description>P2P web search</Description>\n"
        f'  <Url type="text/html" template="http://{host}/'
        'yacysearch.html?query={searchTerms}"/>\n'
        f'  <Url type="application/rss+xml" template="http://{host}/'
        'yacysearch.rss?query={searchTerms}"/>\n'
        "</OpenSearchDescription>\n").encode()
    return prop


@servlet("Banner")
def banner(header, post, sb) -> ServerObjects:
    """Status banner PNG for embedding (reference: htroot/Banner.java),
    drawn with the framework's own raster/PNG encoder."""
    from ...visualization.raster import RasterPlotter
    p = RasterPlotter(468, 60, background=(8, 8, 32))
    green = (120, 255, 120)
    grey = (180, 180, 200)
    p.text(8, 10, "YaCy-TPU peer: "
           + sb.config.get("peerName", "anon")[:24], green)
    p.text(8, 24, f"documents: {sb.index.doc_count()}", grey)
    seeddb = getattr(sb, "seeddb", None)
    peers = len(seeddb.active_seeds()) if seeddb else 0
    p.text(8, 38, f"peers: {peers}", grey)
    prop = ServerObjects()
    prop.raw_body = p.png_bytes()
    prop.raw_ctype = "image/png"
    return prop


@servlet("Table_YMark_p")
def table_ymark(header, post, sb) -> ServerObjects:
    """Bookmark table browser (reference: htroot/Table_YMark_p.java) —
    the Tables_p surface pinned to the bookmarks table."""
    post.put("table", "bookmarks")
    from .boards import respond_table
    return respond_table(header, post, sb)


@servlet("ViewProfile")
def view_profile(header, post, sb) -> ServerObjects:
    """A peer's public profile (reference: htroot/ViewProfile.html over
    the profile RPC)."""
    prop = ServerObjects()
    name = post.get("peer", "")
    node = getattr(sb, "node", None)
    seeddb = getattr(sb, "seeddb", None) or getattr(node, "seeddb", None)
    prop.put("found", 0)
    if name and node is not None and seeddb is not None:
        for s in seeddb.all_seeds():
            if s.name == name:
                profile = node.protocol.profile(s)
                prop.put("found", 1)
                prop.put("peer", escape_html(name))
                items = sorted((profile or {}).items())
                prop.put("fields", len(items))
                for i, (k, v) in enumerate(items):
                    prop.put(f"fields_{i}_key", escape_html(str(k)))
                    prop.put(f"fields_{i}_value", escape_html(str(v)))
                break
    return prop


@servlet("NetworkHistory")
def network_history(header, post, sb) -> ServerObjects:
    """Network size over time from the peer-ping event series
    (reference: htroot/NetworkHistory.java)."""
    from ...utils import eventtracker as et
    prop = ServerObjects()
    events = et.events(et.EClass.PEERPING)[-200:]
    prop.put("points", len(events))
    for i, e in enumerate(events):
        prop.put(f"points_{i}_ts", int(e.ts))
        prop.put(f"points_{i}_count", e.count)
    seeddb = getattr(sb, "seeddb", None)
    prop.put("now", len(seeddb.active_seeds()) if seeddb else 0)
    return prop


@servlet("ContentControl_p")
def content_control(header, post, sb) -> ServerObjects:
    """Bookmark-driven content-control config (reference:
    htroot/ContentControl_p.java): urls bookmarked with the control tag
    are excluded from search results."""
    prop = ServerObjects()
    cc = sb.content_control
    if post.get("set"):
        sb.config.set("contentcontrol.enabled",
                      "true" if post.get("enabled") else "false")
        # the filter gate reads the OBJECT's flag (switchboard search
        # path) — the toggle must apply live, not at next restart
        cc.enabled = bool(post.get("enabled"))
        if post.get("tag"):
            cc.control_tag = post.get("tag")
        prop.put("saved", 1)
    cc.update_filter_job()
    prop.put("enabled",
             1 if sb.config.get_bool("contentcontrol.enabled", False)
             else 0)
    prop.put("tag", escape_html(cc.control_tag))
    prop.put("entries", cc.size())
    return prop


@servlet("IndexShare_p")
def index_share(header, post, sb) -> ServerObjects:
    """Index-sharing switches (reference: htroot/IndexShare_p.java):
    whether this peer answers remote searches and accepts DHT
    transfers; the api/share upload surface is the `share` servlet."""
    prop = ServerObjects()
    if post.get("set"):
        for key in ("allowRemoteSearch", "allowReceiveIndex"):
            sb.config.set(key, "true" if post.get(key) else "false")
        prop.put("saved", 1)
    prop.put("allowRemoteSearch",
             1 if sb.config.get_bool("allowRemoteSearch", True) else 0)
    prop.put("allowReceiveIndex",
             1 if sb.config.get_bool("allowReceiveIndex", True) else 0)
    prop.put("doccount", sb.index.doc_count())
    prop.put("rwicount", sb.index.rwi_size())
    return prop


@servlet("ConfigProfile_p")
def config_profile(header, post, sb) -> ServerObjects:
    """This node's public operator profile (reference:
    htroot/ConfigProfile_p.java; served to peers by the profile RPC)."""
    prop = ServerObjects()
    fields = ("name", "nickname", "homepage", "email", "comment")
    if post.get("save"):
        for f in fields:
            sb.config.set(f"profile.{f}", post.get(f, ""))
        prop.put("saved", 1)
    prop.put("fields", len(fields))
    for i, f in enumerate(fields):
        prop.put(f"fields_{i}_key", f)
        prop.put(f"fields_{i}_value",
                 escape_html(sb.config.get(f"profile.{f}", "")))
        prop.put(f"fields_{i}_eol", 1 if i < len(fields) - 1 else 0)
    return prop
