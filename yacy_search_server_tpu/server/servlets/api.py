"""Machine API servlets — htroot/api/* equivalents.

Capability equivalents of the reference's machine endpoints (reference:
htroot/api/status_p.java, termlist_p.java, webstructure.java,
citation.java, linkstructure.java, timeline_p.java, latency_p.java).
All emit JSON through templates or direct property maps.
"""

from __future__ import annotations

from ...utils.hashes import url2hash, word2hash
from ..objects import ServerObjects, escape_json
from . import servlet


@servlet("feed")
def respond_feed(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Event channels as RSS (reference: peers/EventChannel.java +
    htroot/api/feed.java — recent node events streamed as feeds).
    Channels: LOCALSEARCH (query log), NEWS (incoming news records),
    INDEX (indexing counters)."""
    import time as _time
    from ..objects import escape_xml
    prop = ServerObjects()
    channel = post.get("set", "LOCALSEARCH").upper()
    count = min(max(post.get_int("count", 20), 1), 100)
    items: list[tuple[str, str, float]] = []
    if channel == "LOCALSEARCH":
        for e in sb.access_tracker.latest(count):
            items.append((f"query: {e.query}",
                          f"{e.result_count} results in {e.time_ms:.0f} ms",
                          e.timestamp))
    elif channel == "NEWS":
        pool = getattr(sb, "news", None)   # set by P2PNode; absent solo
        if pool is not None:
            for rec in pool.incoming()[:count]:
                items.append((f"news: {rec.category}",
                              str(rec.attributes), rec.created))
    elif channel == "INDEX":
        items.append((f"indexed documents: {sb.index.doc_count()}",
                      f"rwi postings: {sb.index.rwi_size()}", _time.time()))
    rows = []
    from email.utils import formatdate
    for title, desc, ts in items:
        pub = formatdate(ts, usegmt=True)   # RFC-822, locale-independent
        rows.append(f"<item><title>{escape_xml(title)}</title>"
                    f"<description>{escape_xml(desc)}</description>"
                    f"<pubDate>{pub}</pubDate></item>")
    prop.raw_body = (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<rss version="2.0"><channel>'
        f"<title>yacy-tpu feed: {escape_xml(channel)}</title>"
        + "".join(rows) + "</channel></rss>")
    prop.raw_ctype = "application/rss+xml; charset=utf-8"
    return prop


@servlet("postprocessing_p")
def respond_postprocessing(header: dict, post: ServerObjects,
                           sb) -> ServerObjects:
    """Trigger citation-rank postprocessing (reference: the postprocessing
    control on IndexControl; BlockRank evaluation)."""
    prop = ServerObjects()
    from ...ops.blockrank import (host_ranks, host_ranks_from_edges,
                                  postprocess_segment)
    # prefer the per-edge webgraph when it has data (richer than the
    # host matrix: per-edge retirement on re-index, nofollow carried)
    if len(sb.index.webgraph):
        all_ranks = host_ranks_from_edges(sb.index.webgraph)
        prop.put("source", "webgraph")
    else:
        all_ranks = host_ranks(sb.web_structure)
        prop.put("source", "hostmatrix")
    if post.get("run"):
        prop.put("updated", postprocess_segment(
            sb.index, sb.web_structure, ranks=all_ranks))
        from ...index.postprocess import postprocess_uniqueness
        prop.put("uniqueness_updated", postprocess_uniqueness(sb.index))
    ranks = sorted(all_ranks.items(),
                   key=lambda kv: -kv[1])[: post.get_int("maxhosts", 25)]
    prop.put("hosts", len(ranks))
    for i, (h, r) in enumerate(ranks):
        prop.put(f"hosts_{i}_host", escape_json(h))
        prop.put(f"hosts_{i}_rank", round(r, 6))
    return prop


@servlet("termlist_p")
def respond_termlist(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Term census of the local RWI (reference: htroot/api/termlist_p.java)."""
    prop = ServerObjects()
    maxn = post.get_int("maxlisted", 100)
    rows = []
    rwi = sb.index.rwi
    hashes = rwi.term_hashes()
    for th in hashes:
        rows.append((th, rwi.count(th)))
    rows.sort(key=lambda t: -t[1])
    rows = rows[:maxn]
    prop.put("termcount", len(hashes))
    prop.put("terms", len(rows))
    for i, (th, c) in enumerate(rows):
        prop.put(f"terms_{i}_hash", th.decode("ascii", "replace"))
        prop.put(f"terms_{i}_count", c)
        prop.put(f"terms_{i}_eol", 1 if i < len(rows) - 1 else 0)
    return prop


@servlet("webstructure")
def respond_webstructure(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Host-level link structure (reference: htroot/api/webstructure.java)."""
    prop = ServerObjects()
    ws = sb.web_structure
    about = post.get("about", "").strip()
    if about:
        hosts = [about] if ws.references_count(about) or ws.outgoing(about) else []
    else:
        hosts = [h for h, _ in ws.top_hosts(post.get_int("maxhosts", 50))]
    prop.put("hosts", len(hosts))
    for i, h in enumerate(hosts):
        pre = f"hosts_{i}_"
        out = ws.outgoing(h)
        prop.put(pre + "host", escape_json(h))
        prop.put(pre + "references", ws.references_count(h))
        targets = sorted(out.items(), key=lambda t: -t[1])
        prop.put(pre + "targets", len(targets))
        for j, (t, c) in enumerate(targets):
            prop.put(f"{pre}targets_{j}_host", escape_json(t))
            prop.put(f"{pre}targets_{j}_count", c)
            prop.put(f"{pre}targets_{j}_eol", 1 if j < len(targets) - 1 else 0)
        prop.put(pre + "eol", 1 if i < len(hosts) - 1 else 0)
    return prop


@servlet("citation")
def respond_citation(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Inbound citations of one URL (reference: htroot/api/citation.java)."""
    prop = ServerObjects()
    url = post.get("url", "").strip()
    prop.put("url", escape_json(url))
    prop.put("citations", 0)
    if not url:
        return prop
    h = url2hash(url)
    metas = []
    for docid in sb.index.citations.citing_docids(h):
        m = sb.index.metadata.get(docid)
        if m is not None:
            metas.append(m)
    prop.put("citations", len(metas))
    for i, m in enumerate(metas):
        prop.put(f"citations_{i}_url", escape_json(m.get("sku", "")))
        prop.put(f"citations_{i}_eol", 1 if i < len(metas) - 1 else 0)
    return prop


@servlet("blacklists_p")
def respond_blacklists(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Blacklist CRUD (reference: htroot/Blacklist_p.java +
    htroot/api/blacklists/*)."""
    prop = ServerObjects()
    bl = sb.blacklist
    action = post.get("action", "")
    if action == "add" and post.get("entry"):
        bl.add(post.get("list", "default"), post.get("entry"))
    elif action == "delete" and post.get("entry"):
        bl.remove(post.get("list", "default"), post.get("entry"))
    lists = bl.list_names()
    prop.put("lists", len(lists))
    for i, name in enumerate(lists):
        entries = bl.entries(name)
        pre = f"lists_{i}_"
        prop.put(pre + "name", escape_json(name))
        prop.put(pre + "entries", len(entries))
        for j, e in enumerate(entries):
            prop.put(f"{pre}entries_{j}_pattern", escape_json(e))
            prop.put(f"{pre}entries_{j}_eol", 1 if j < len(entries) - 1 else 0)
        prop.put(pre + "eol", 1 if i < len(lists) - 1 else 0)
    return prop


@servlet("getpageinfo")     # the reference ships both mounts
@servlet("getpageinfo_p")
def respond_pageinfo(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Fetch+parse a page for the crawl-start UI preview (reference:
    htroot/api/getpageinfo_p.java)."""
    prop = ServerObjects()
    url = post.get("url", "").strip()
    prop.put("url", escape_json(url))
    prop.put("title", "")
    prop.put("robots-allowed", 1)
    prop.put("links", 0)
    if not url:
        return prop
    # SSRF guard (server/netguard.py): this servlet fetches a
    # user-supplied URL — and the bare `getpageinfo` mount is PUBLIC —
    # so loopback/self targets are refused outright and the same
    # predicate rides every redirect hop. Non-admin callers are also
    # refused link-local (cloud metadata) and LAN targets, with the
    # connection pinned to the vetted resolution (DNS-rebinding);
    # admins keep private targets (probing an intranet crawl start is
    # the UI's normal use).
    from ..netguard import refuse_addr, unsafe_target
    allow_private = bool(header.get("admin"))
    if unsafe_target(url, sb.loader, allow_private=allow_private):
        prop.put("error", "target refused")
        return prop
    try:
        from ...crawler.request import Request
        resp = sb.loader.load(
            Request(url=url),
            url_filter=lambda u: not unsafe_target(
                u, sb.loader, allow_private=allow_private),
            addr_guard=(None if sb.loader.transport is not None else
                        (lambda a: refuse_addr(a, allow_private))))
        from ...document.parser.registry import parse_source
        docs = parse_source(url, resp.mime_type(), resp.content)
        if docs:
            doc = docs[0]
            prop.put("title", escape_json(doc.title))
            n = min(len(doc.anchors), 200)
            prop.put("links", n)
            for i, a in enumerate(doc.anchors[:n]):
                prop.put(f"links_{i}_url", escape_json(a.url))
                prop.put(f"links_{i}_eol", 1 if i < n - 1 else 0)
        prop.put("robots-allowed", 1 if sb.robots.is_allowed(url) else 0)
    except Exception as e:
        prop.put("error", escape_json(str(e)))
    return prop


@servlet("linkstructure")
def respond_linkstructure(header: dict, post: ServerObjects,
                          sb) -> ServerObjects:
    """Hyperlink structure of one host from the per-edge webgraph store
    (reference: htroot/api/linkstructure.java — edges with source/target
    paths, Inbound/Outbound type, and per-node link depth from the host
    root, computed there by HyperlinkGraph.findLinkDepth)."""
    prop = ServerObjects()
    about = post.get("about", "").strip()
    prop.put("edges", 0)
    prop.put("maxdepth", 0)
    if not about:
        return prop
    host = about
    if "://" in about:
        from ...utils.hashes import safe_host
        host = safe_host(about)
    maxnodes = min(post.get_int("maxnodes", 10000), 10000)
    wg = sb.index.webgraph
    inhost, outbound = wg.host_link_graph(host)
    edges = (inhost + outbound)[:maxnodes]

    # link depth per in-host path: BFS from the host root ("/" when linked,
    # else the shortest source path — HyperlinkGraph's root choice)
    adj: dict[str, list[str]] = {}
    nodes = set()
    for e in inhost:
        adj.setdefault(e["source_path_s"], []).append(e["target_path_s"])
        nodes.add(e["source_path_s"])
        nodes.add(e["target_path_s"])
    depth: dict[str, int] = {}
    if nodes:
        # root = "/" when linked; else the shortest SOURCE path (a node
        # with out-edges — a leaf target can never seed the BFS), with a
        # lexicographic tie-break for deterministic depths
        root = "/" if "/" in nodes else min(sorted(adj), key=len)
        frontier = [root]
        depth[root] = 0
        while frontier:
            nxt = []
            for p in frontier:
                for q in adj.get(p, ()):
                    if q not in depth:
                        depth[q] = depth[p] + 1
                        nxt.append(q)
            frontier = nxt
    maxdepth = max(depth.values(), default=0)

    prop.put("edges", len(edges))
    prop.put("maxdepth", maxdepth)
    for i, e in enumerate(edges):
        pre = f"edges_{i}_"
        outb = not e["target_inbound_b"]
        prop.put(pre + "source", escape_json(e["source_path_s"]))
        prop.put(pre + "target", escape_json(
            e["target_sku_s"] if outb else e["target_path_s"]))
        prop.put(pre + "type", "Outbound" if outb else "Inbound")
        prop.put(pre + "depthSource", depth.get(e["source_path_s"], -1))
        prop.put(pre + "depthTarget", depth.get(e["target_path_s"], -1)
                 if not outb else -1)
        prop.put(pre + "eol", 1 if i < len(edges) - 1 else 0)
    return prop


@servlet("schema")
def respond_schema(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Metadata schema listing (reference: htroot/api/schema.java — the
    active field set, here the columnar store's full field census)."""
    from ...index.metadata import DOUBLE_FIELDS, INT_FIELDS, TEXT_FIELDS
    prop = ServerObjects()
    rows = ([(f, "text") for f in TEXT_FIELDS]
            + [(f, "int") for f in INT_FIELDS]
            + [(f, "double") for f in DOUBLE_FIELDS])
    prop.put("fields", len(rows))
    for i, (name, ftype) in enumerate(rows):
        prop.put(f"fields_{i}_name", name)
        prop.put(f"fields_{i}_type", ftype)
        prop.put(f"fields_{i}_eol", 1 if i < len(rows) - 1 else 0)
    return prop


@servlet("snapshot")
def respond_snapshot(header: dict, post: ServerObjects,
                     sb) -> ServerObjects:
    """Stored page snapshot retrieval (reference: htroot/api/snapshot.java
    — serve the archived rendition of a url)."""
    prop = ServerObjects()
    url = post.get("url", "").strip()
    revisions = sb.snapshots.revisions(url) if url else []
    if revisions:
        data = sb.snapshots.load(revisions[-1])
        prop.raw_body = data
        prop.raw_ctype = "text/html; charset=utf-8"
        return prop
    prop.put("url", escape_json(url))
    prop.put("revisions", 0)
    return prop


@servlet("status_p")
def respond_status_api(header: dict, post: ServerObjects,
                       sb) -> ServerObjects:
    """Machine status endpoint (reference: htroot/api/status_p.java —
    index sizes, queue fill, memory in one JSON)."""
    from ...crawler.frontier import StackType
    from ...utils.memory import MemoryControl
    prop = ServerObjects()
    prop.put("urlpublictextSize", sb.index.doc_count())
    prop.put("rwipublictextSize", sb.index.rwi_size())
    prop.put("webgraphSize", len(sb.index.webgraph))
    prop.put("localcrawljobs", sb.noticed.size(StackType.LOCAL))
    prop.put("memoryUsed_kb", MemoryControl.used() // 1024)
    prop.put("memoryFree_kb", MemoryControl.available() // 1024)
    return prop


@servlet("latency_p")
def respond_latency(header: dict, post: ServerObjects,
                    sb) -> ServerObjects:
    """Per-host crawl latency table (reference:
    htroot/api/latency_p.java over the Latency politeness model)."""
    prop = ServerObjects()
    snap = sb.latency.snapshot()
    hosts = sorted(snap)[:post.get_int("maxhosts", 100)]
    prop.put("hosts", len(hosts))
    for i, h in enumerate(hosts):
        st = snap[h]
        prop.put(f"hosts_{i}_host", escape_json(h))
        prop.put(f"hosts_{i}_average_ms", int(st.average_s * 1000))
        prop.put(f"hosts_{i}_count", st.count)
        prop.put(f"hosts_{i}_eol", 1 if i < len(hosts) - 1 else 0)
    return prop


@servlet("timeline_p")
def respond_timeline(header: dict, post: ServerObjects,
                     sb) -> ServerObjects:
    """Query timeline (reference: htroot/api/timeline_p.java — recent
    searches as a time series from the AccessTracker)."""
    prop = ServerObjects()
    entries = sb.access_tracker.latest(post.get_int("count", 100))
    prop.put("events", len(entries))
    for i, e in enumerate(entries):
        prop.put(f"events_{i}_time", int(e.timestamp))
        prop.put(f"events_{i}_query", escape_json(e.query))
        prop.put(f"events_{i}_resultcount", e.result_count)
        prop.put(f"events_{i}_ms", int(e.time_ms))
        prop.put(f"events_{i}_eol", 1 if i < len(entries) - 1 else 0)
    return prop


@servlet("version")
def respond_version(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Version probe (reference: htroot/api/version.java)."""
    from ... import __version__
    prop = ServerObjects()
    prop.put("version", __version__)
    prop.put("versionstring", f"yacy-tpu/{__version__}")
    return prop


@servlet("blacklists")
def respond_blacklists_public(header: dict, post: ServerObjects,
                              sb) -> ServerObjects:
    """Read-only blacklist listing (reference: htroot/api/blacklists.java
    — the public read twin of blacklists_p)."""
    prop = ServerObjects()
    names = sb.blacklist.list_names()
    prop.put("lists", len(names))
    for i, name in enumerate(names):
        prop.put(f"lists_{i}_name", escape_json(name))
        prop.put(f"lists_{i}_entries", len(sb.blacklist.entries(name)))
        prop.put(f"lists_{i}_eol", 1 if i < len(names) - 1 else 0)
    return prop


@servlet("config_p")
def respond_config_api(header: dict, post: ServerObjects,
                       sb) -> ServerObjects:
    """Config get/set over the API (reference: htroot/api/config_p.java:
    ?key=K reads, ?key=K&value=V writes; the change is API-recorded like
    every admin action)."""
    prop = ServerObjects()
    key = post.get("key", "").strip()
    prop.put("key", escape_json(key))
    if key:
        if post.get("value", None) is not None:
            sb.config.set(key, post.get("value"))
            sb.work_tables.record_api_call(
                f"config_p.json?key={key}&value={post.get('value')}",
                "config_p", f"set {key}")
        prop.put("value", escape_json(str(sb.config.get(key, ""))))
    else:
        prop.put("value", "")
    return prop


@servlet("yacydoc")
def respond_yacydoc(header: dict, post: ServerObjects,
                    sb) -> ServerObjects:
    """One document's metadata by urlhash or url (reference:
    htroot/api/yacydoc.java — the dc_* record of an indexed page)."""
    from ...utils.hashes import url2hash
    prop = ServerObjects()
    uh = post.get("urlhash", "").strip().encode("ascii", "replace")
    if not uh and post.get("url", ""):
        uh = url2hash(post.get("url"))
    docid = sb.index.metadata.docid(uh) if uh else None
    prop.put("found", 0 if docid is None else 1)
    if docid is None:
        return prop
    row = sb.index.metadata.row(docid)
    prop.put("urlhash", uh.decode("ascii", "replace"))
    prop.put("url", escape_json(row.get("sku", "")))
    prop.put("dc_title", escape_json(row.get("title", "")))
    prop.put("dc_creator", escape_json(row.get("author", "")))
    prop.put("dc_description", escape_json(row.get("description_txt", "")))
    prop.put("dc_subject", escape_json(row.get("keywords", "")))
    prop.put("dc_publisher", escape_json(row.get("publisher_t", "")))
    prop.put("dc_language", escape_json(row.get("language_s", "")))
    prop.put("size", row.get("size_i", 0))
    prop.put("wordcount", row.get("wordcount_i", 0))
    prop.put("references", row.get("references_i", 0))
    prop.put("host", escape_json(row.get("host_s", "")))
    prop.put("collection", escape_json(row.get("collection_sxt", "")))
    prop.put("last_modified_days", row.get("last_modified_days_i", 0))
    return prop
