"""Federation servlets: Solr-compatible select, external push, dumps.

Capability equivalents of the reference's federation-facing endpoints
(reference: source/net/yacy/http/servlets/SolrSelectServlet.java — the
Solr-compatible /solr/select surface other peers and tools shard-read
from; htroot/api/push_p.java — external document push; htroot/
IndexExport_p.java — full-index dump export/restore)."""

from __future__ import annotations

import json
import os

from ...document.document import Document
from ...index.metadata import DOUBLE_FIELDS, INT_FIELDS, TEXT_FIELDS
from ...utils.hashes import url2hash
from ..objects import ServerObjects
from . import servlet


@servlet("solr/select")      # the reference's mount point
@servlet("select")
def respond_select(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Solr-shaped select: q (free text, field:value, id:<hash>, *:*),
    start/rows/fl; JSON body in the solrj wire shape so shard readers and
    external Solr clients keep working (SolrSelectServlet parity)."""
    prop = ServerObjects()
    q = post.get("q", "*:*").strip()
    rows = min(post.get_int("rows", 10), 1000)
    start = post.get_int("start", 0)
    fl = [f for f in post.get("fl", "").split(",") if f]

    docs: list[dict] = []
    num_found = 0
    meta = sb.index.metadata

    def row_of(docid: int, score: int = 0) -> dict | None:
        m = meta.get(docid)
        if m is None:
            return None
        row = {"id": m.urlhash.decode("ascii", "replace"), "score": score}
        for k in (*TEXT_FIELDS, *INT_FIELDS, *DOUBLE_FIELDS):
            v = m.get(k)
            if v not in (None, ""):
                row[k] = v
        if fl:
            row = {k: v for k, v in row.items() if k in fl or k == "id"}
        return row

    if q in ("*:*", "*", ""):
        num_found = sb.index.doc_count()
        taken = 0
        for docid in range(meta.capacity()):
            if meta.is_deleted(docid):
                continue
            if taken < start:
                taken += 1
                continue
            if len(docs) >= rows:
                break
            r = row_of(docid)
            if r is not None:
                docs.append(r)
            taken += 1
    elif q.startswith("id:"):
        uh = q[3:].strip().strip('"').encode("ascii", "replace")
        docid = meta.docid(uh)
        if docid is not None and not meta.is_deleted(docid):
            r = row_of(docid)
            if r is not None:
                docs, num_found = [r], 1
    else:
        # field:value terms and free text both route through the normal
        # query model (field queries map onto modifiers where they exist)
        querystring = q.replace("host_s:", "site:") \
                       .replace("url_file_ext_s:", "filetype:")
        ev = sb.search(querystring, count=rows + start)
        results = ev.results(offset=start, count=rows)
        num_found = ev.result_heap.size_available()
        for r in results:
            if r.docid >= 0:
                row = row_of(r.docid, score=int(r.score))
            else:       # remote entry: serve the fields it carried
                row = {"id": r.urlhash.decode("ascii", "replace"),
                       "sku": r.url, "title": r.title, "host_s": r.host,
                       "score": int(r.score)}
                if fl:
                    row = {k: v for k, v in row.items()
                           if k in fl or k == "id"}
            if row is not None:
                docs.append(row)

    # qf= field boosts re-rank the page (Boost.java query algebra): each
    # row scores as sum(boost * matched-term fraction) over the spec
    qf = post.get("qf", "").strip()
    if qf and docs:
        from ...index.federate import boosted_score, parse_boosts
        boosts = parse_boosts(qf)
        terms = [t for t in q.split() if ":" not in t]
        docs.sort(key=lambda d: -boosted_score(d, terms, boosts))

    wt = post.get("wt", "json")
    if wt == "csv":
        # flat writer (the reference's flat-text/CSV response writers,
        # cora/federate/solr/responsewriter): header row + one doc/line
        cols = fl or ["id", "sku", "title", "host_s", "score"]
        lines = [",".join(cols)]
        for d in docs:
            lines.append(",".join(
                '"' + str(d.get(c, "")).replace('"', '""') + '"'
                for c in cols))
        prop.raw_body = "\n".join(lines) + "\n"
        prop.raw_ctype = "text/csv; charset=utf-8"
        return prop
    prop.raw_body = json.dumps({
        "responseHeader": {"status": 0, "QTime": 0,
                           "params": {"q": q, "rows": str(rows),
                                      "start": str(start)}},
        "response": {"numFound": num_found, "start": start, "docs": docs},
    }, ensure_ascii=False)
    return prop


@servlet("api/push_p")       # the reference's mount point
@servlet("push_p")
def respond_push(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """External document push/delete (htroot/api/push_p.java): index a
    document supplied by an external producer, no crawl involved."""
    prop = ServerObjects()
    if post.get("delete"):
        uh = post.get("delete").encode("ascii", "replace")
        prop.put("deleted", 1 if sb.index.remove_document(uh) else 0)
        return prop
    url = post.get("url", "")
    if not url:
        prop.put("stored", 0)
        prop.put("info", "missing url")
        return prop
    doc = Document(
        url=url, title=post.get("title", ""),
        text=post.get("content", ""), author=post.get("author", ""),
        description=post.get("description", ""),
        keywords=[k for k in post.get("keywords", "").split(",") if k],
        language=post.get("language", ""),
        publish_date_days=post.get_int("lastmod_days", 0),
        lat=float(post.get("lat", "0") or 0),
        lon=float(post.get("lon", "0") or 0))
    docid = sb.index.store_document(doc, collection=post.get(
        "collection", "api"))
    prop.put("stored", 1)
    prop.put("docid", docid)
    prop.put("urlhash", url2hash(url).decode("ascii", "replace"))
    return prop


@servlet("opensearchdescription")
def respond_osd(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """OpenSearch description document (reference:
    htroot/opensearchdescription.java) — lets browsers/aggregators
    register this node as a search provider."""
    from ..objects import escape_xml
    prop = ServerObjects()
    name = sb.config.get("promoteSearchPageGreeting", "YaCy-TPU Search")
    # absolute URLs from the request host: saved/offline copies of this
    # document must still resolve (the reference builds them the same way).
    # The Host header is client-controlled: escape it like any attribute.
    base = escape_xml("http://" + header.get("host", "127.0.0.1:8090"))
    prop.raw_body = (
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<OpenSearchDescription xmlns="http://a9.com/-/spec/opensearch/1.1/">'
        f"<ShortName>{escape_xml(name[:16])}</ShortName>"
        f"<Description>{escape_xml(name)} P2P web search</Description>"
        f'<Url type="application/rss+xml" template="{base}'
        '/yacysearch.rss?query={searchTerms}&amp;startRecord={startIndex?}"/>'
        f'<Url type="text/html" template="{base}'
        '/yacysearch.html?query={searchTerms}"/>'
        "<InputEncoding>UTF-8</InputEncoding>"
        "</OpenSearchDescription>")
    prop.raw_ctype = "application/opensearchdescription+xml; charset=utf-8"
    return prop


@servlet("IndexExport_p")
def respond_export(header: dict, post: ServerObjects, sb) -> ServerObjects:
    """Dump export/import under DATA/EXPORT (htroot/IndexExport_p.java)."""
    from ...index.dumps import export_dump, import_dump
    prop = ServerObjects()
    base = os.path.join(sb.data_dir, "EXPORT") if sb.data_dir else None
    if base is None:
        prop.put("info", "no data dir")
        return prop
    os.makedirs(base, exist_ok=True)
    name = os.path.basename(post.get("file", "") or "dump.jsonl.gz")
    path = os.path.join(base, name)
    if post.get("action") == "export":
        n = export_dump(sb.index, path,
                        query_host=post.get("host", "") or None)
        prop.put("exported", n)
        prop.put("file", name)
    elif post.get("action") == "import" and os.path.exists(path):
        n = import_dump(sb.index, path)
        prop.put("imported", n)
    dumps = sorted(f for f in os.listdir(base))
    prop.put("dumps", len(dumps))
    for i, f in enumerate(dumps):
        prop.put(f"dumps_{i}_file", f)
        prop.put(f"dumps_{i}_size", os.path.getsize(os.path.join(base, f)))
    return prop
