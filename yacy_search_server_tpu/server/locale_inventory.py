"""UI string inventory — what a complete .lng locale must translate.

The reference ships ~15 full locales built with its Translator tool over
the htroot templates (reference: locales/*.lng, TranslatorTest). This
module is the completeness oracle for ours: it extracts every
operator-visible string from the shipped templates — text nodes between
tags and button/placeholder attribute values — normalized to the exact
``>text<`` / ``value="text"`` replacement forms the translation engine
applies, so a locale file is complete when it carries a pair for every
inventory entry (brand names and untranslatable tokens excluded).
"""

from __future__ import annotations

import os
import re

HTROOT = os.path.join(os.path.dirname(__file__), "htroot")

_PLACEHOLDER_RE = re.compile(r"#\[[^\]]*\]#|#\(/?[^)]*\)#|#\{/?[^}]*\}#"
                             r"|#%[^%]*%#")
_TEXT_RE = re.compile(r">([^<>]+)<")
# attribute strings are extracted per-TAG so protocol values (hidden
# form fields) can be excluded — translating value="create" would break
# the form handler comparing action == "create"
_TAG_RE = re.compile(r"<(?:input|button|textarea)[^>]*>")
_ATTR_RE = re.compile(r'(value|placeholder)="([^"#]+)"')

# strings a locale need not translate: brand identity, numbers/units,
# pure punctuation, protocol tokens
_SKIP = re.compile(
    r"^[\s\d\W]*$|^YaCy|^TPU$|^APIs?$|^/|^http|^#|^::|"
    r"^(ms|kB|MB|GB|q/s|json|rss|xml|csv|html|true|false)$",
    re.IGNORECASE)


def template_names() -> list[str]:
    out = []
    for root, _dirs, files in os.walk(HTROOT):
        for f in files:
            if f.endswith((".html", ".template")):
                out.append(os.path.relpath(os.path.join(root, f), HTROOT))
    return sorted(out)


def strings_of(template: str) -> list[str]:
    """Translatable replacement-form strings of one template."""
    with open(os.path.join(HTROOT, template), encoding="utf-8") as f:
        source = f.read()
    # drop script/style bodies (not operator-visible prose) and template
    # placeholders (dynamic content is never translated)
    source = re.sub(r"<script.*?</script>", "", source, flags=re.S)
    source = re.sub(r"<style.*?</style>", "", source, flags=re.S)
    cleaned = _PLACEHOLDER_RE.sub("\x00", source)
    out: list[str] = []
    seen: set[str] = set()
    for m in _TEXT_RE.finditer(cleaned):
        text = m.group(1)
        if "\x00" in text or "\n" in text:
            continue
        if _SKIP.match(text.strip()) or not text.strip():
            continue
        form = f">{text}<"
        if form not in seen:
            seen.add(form)
            out.append(form)
    for tag_m in _TAG_RE.finditer(cleaned):
        tag = tag_m.group(0)
        if 'type="hidden"' in tag:
            continue          # protocol value, never operator-visible
        for m in _ATTR_RE.finditer(tag):
            val = m.group(2)
            if _SKIP.match(val.strip()):
                continue
            form = f'{m.group(1)}="{val}"'
            if form not in seen:
                seen.add(form)
                out.append(form)
    return out


def inventory() -> dict[str, list[str]]:
    """template -> replacement-form strings (empty lists dropped)."""
    out: dict[str, list[str]] = {}
    for t in template_names():
        strs = strings_of(t)
        if strs:
            out[t] = strs
    return out


def missing_in(table, inv: dict[str, list[str]] | None = None) -> list[str]:
    """Inventory entries a loaded TranslationTable does not cover.

    Coverage is PER TEMPLATE, matching translate()'s runtime rule: the
    global section applies everywhere, a named section only to its own
    template — a pair filed under Settings_p.html must not count as
    covering Ranking_p.html."""
    inv = inv or inventory()
    global_cov = {src for src, _dst in table._sections.get("*", [])}
    out = []
    for t, strs in inv.items():
        local = {src for src, _dst in
                 table._sections.get(os.path.basename(t), [])}
        for s in strs:
            if s not in global_cov and s not in local:
                out.append(f"{t}: {s}")
    return out
