"""Roofline profiler — measured kernel walls paired with cost models.

The measurement half of the silicon accounting (ops/roofline.py is the
analytical half): serving paths report (kernel, wall, shape) here; the
profiler converts each report into achieved FLOP/s, achieved GB/s and a
%-of-peak number against the device's declared ceiling, and keeps bounded
per-kernel series so the rank-service stats, the Performance_Roofline_p
servlet and bench artifacts can all read one surface.

Design constraints:

- **Hot-path cheap**: one `record()` is a cost-model closure call (a few
  float ops) + a deque append under a lock — the profiler-overhead test
  pins < 1% added latency on a 1k-query microbench. No jax, no syscalls.
- **Pairs with the event tracker**: wall times the serving path already
  measures (devstore's per-dispatch kernel walls, eventtracker
  StageTimer stages) feed `record()` directly; nothing is re-timed.
- **Per-query attribution**: a batched dispatch serving `queries` slots
  records the batch once for kernel aggregates AND per-query utilization
  samples (each query's share of the dispatch), which is what
  `util_pct` p50/p95 in the rank-service counters summarizes.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..ops import roofline
from ..ops.roofline import Cost, DevicePeak, RooflinePoint, roofline_point
from . import tracing


class RooflineProfiler:
    """Bounded per-kernel roofline series over measured walls."""

    def __init__(self, peak: DevicePeak | None = None, maxlen: int = 4096):
        self._peak = peak
        self._lock = threading.Lock()
        self._series: dict[str, deque] = {}   # kernel -> (wall_s, Cost)
        self._query_util: deque = deque(maxlen=20_000)  # (util, bound)
        self._maxlen = maxlen
        # serving shapes are highly repetitive (same bs/tile/k dispatch
        # after dispatch): memoizing the cost closure keeps record() at
        # ~1-2 µs — the <1%-overhead contract on a sub-ms query path
        self._cost_memo: dict = {}
        self.enabled = True

    @property
    def peak(self) -> DevicePeak:
        if self._peak is None:
            self._peak = roofline.device_peak()
        return self._peak

    def set_peak(self, peak: DevicePeak) -> None:
        self._peak = peak

    # -- recording -----------------------------------------------------------

    def record(self, kernel: str, wall_s: float, queries: int = 0,
               **shape) -> None:
        """One measured kernel execution. `shape` feeds the kernel's cost
        model; `queries` > 0 additionally files per-query utilization
        samples (each query in the batch experienced this dispatch)."""
        if not self.enabled:
            return
        # tracing bridge: a kernel wall measured under an active trace
        # becomes a child span — nothing is re-timed (solo dispatches run
        # on the query's own thread; batched dispatches have no trace
        # context here and emit theirs from the submitter instead).
        # Guarded here so the untraced hot path pays one contextvar
        # read, not a name allocation (record() is pinned < 10 µs)
        if tracing.current() is not None:
            tracing.emit(f"kernel.{kernel}", wall_s * 1000.0,
                         queries=queries)
        # insertion order is stable per call site, so the unsorted item
        # tuple memoizes just as well (worst case: one extra entry per
        # distinct kwarg order)
        key = (kernel, tuple(shape.items()))
        c = self._cost_memo.get(key)
        if c is None:
            try:
                c = roofline.cost(kernel, **shape)
            except (KeyError, TypeError):
                return  # unregistered kernel/shape must never hurt serving
            if len(self._cost_memo) > 4096:   # unbounded shapes can't leak
                self._cost_memo.clear()
            self._cost_memo[key] = c
        peak = self._peak
        if peak is None:
            peak = self.peak
        with self._lock:
            d = self._series.get(kernel)
            if d is None:
                d = self._series[kernel] = deque(maxlen=self._maxlen)
            d.append((wall_s, c))
            if queries > 0:
                # inline roofline_point: this is the per-query hot path
                w = wall_s if wall_s > 1e-9 else 1e-9
                if c.flops * peak.bytes_per_s < c.bytes * peak.flops_per_s:
                    util = 100.0 * c.bytes / w / peak.bytes_per_s
                    bound = "memory"
                else:
                    util = 100.0 * c.flops / w / peak.flops_per_s
                    bound = "compute"
                self._query_util.extend([(util, bound)] * queries)

    def time(self, kernel: str, queries: int = 0, **shape):
        """Context manager measuring a block's wall into `record`."""
        return _Timed(self, kernel, queries, shape)

    # -- reading -------------------------------------------------------------

    # one nearest-rank convention across the observability layer
    _pctl = staticmethod(tracing._pctl)

    def query_util(self) -> dict:
        """Per-query utilization summary for the rank-service stats."""
        with self._lock:
            samples = list(self._query_util)
        if not samples:
            return {"util_pct_p50": 0.0, "util_pct_p95": 0.0, "bound": ""}
        utils = sorted(u for u, _ in samples)
        mem = sum(1 for _, b in samples if b == "memory")
        return {
            "util_pct_p50": round(self._pctl(utils, 0.50), 3),
            "util_pct_p95": round(self._pctl(utils, 0.95), 3),
            "bound": "memory" if 2 * mem >= len(samples) else "compute",
        }

    def snapshot(self) -> list[RooflinePoint]:
        """One aggregate roofline point per kernel (totals over the
        retained window: total flops/bytes over total wall — the
        throughput view, robust to per-dispatch noise)."""
        with self._lock:
            series = {k: list(d) for k, d in self._series.items()}
        points = []
        for kernel in sorted(series):
            rows = series[kernel]
            wall = sum(w for w, _ in rows)
            fl = sum(c.flops for _, c in rows)
            by = sum(c.bytes for _, c in rows)
            xb = sum(c.xla_bytes for _, c in rows)
            points.append(roofline_point(
                kernel, Cost(fl, by, xb), wall, self.peak))
        return points

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._query_util.clear()


class _Timed:
    __slots__ = ("_p", "_kernel", "_queries", "_shape", "_t0")

    def __init__(self, profiler, kernel, queries, shape):
        self._p = profiler
        self._kernel = kernel
        self._queries = queries
        self._shape = shape

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._p.record(self._kernel, time.perf_counter() - self._t0,
                       self._queries, **self._shape)
        return False


# the process-wide profiler every serving path reports into (mirrors the
# eventtracker's module-global series)
PROFILER = RooflineProfiler()
