"""Env-gated failpoints — deterministic fault injection for robustness
tests (ISSUE 9 satellite; extended by ISSUE 10 into the crash/chaos
harness substrate).

The self-defending serving loop (utils/actuator.py) only transitions on
REAL signals: a burn-rate rule firing, a batcher queue growing, a peer
digest reporting critical.  Testing those transitions organically means
sleeping until enough slow requests accumulate in 30 s histogram
windows — minutes per test.  Failpoints let a test drive the exact same
product code paths deterministically:

- ``servlet.serving`` latency injection: the httpd dispatch sleeps the
  configured milliseconds INSIDE the measured serving wall, so the SLO
  histogram fills with genuinely slow requests and the burn-rate rules
  fire on real data.
- ``batcher.dispatch`` forced worker_stall: a dispatcher sleeps inside
  its dispatch, so the watchdog's stall attribution and the
  worker_stall health rule see a real wedge.
- ``peer.blackhole``: RPCs to the listed peer hashes fail after an
  optional delay — the sick-peer avoidance path sees a genuinely
  unresponsive peer without a real network.

Crash/IO faults (ISSUE 10 tentpole b — the chaos harness drives the
durability claims through the REAL write paths instead of trusting the
fsync comments):

- ``proc.crashpoint``: named SIGKILL barriers inside flush / merge /
  journal-truncate / manifest-switch.  Armed with a crashpoint NAME;
  when execution reaches :func:`crashpoint` with that name the process
  kills itself with ``SIGKILL`` — no atexit, no flush, the honest
  kill−9.  The subprocess harness (tests/test_crash_consistency.py)
  arms each registered name in a child indexer and asserts the restart
  recovers every acked document bit-identically.
- ``io.torn_write``: ``<path_frag>:<n>`` — the next durable write whose
  target path contains ``path_frag`` persists only its first ``n``
  bytes, then raises (the on-disk artifact of a crash mid-write).
- ``io.error``: ``<path_frag>[:<nth>]`` — the nth matching durable
  write raises ``OSError`` (a full disk / dying device at exactly the
  op under test).
- ``device.transfer_fail``: a COUNT of device transfers to fail.  Each
  guarded fetch/upload consumes one charge and raises; at zero the
  device "comes back" — which is how the device-loss tests hold the
  tunnel down across the retry ladder and then let the background
  rebuild succeed (index/devstore.py).  In a multi-process mesh
  (ISSUE 12) the same point armed INSIDE one member process — via the
  ``YACY_FAULTS`` env at spawn or the test-fleet-gated ``meshfault``
  wire endpoint — fails exactly that member's transfers, driving the
  one-member-down survival contract (tests/test_mesh_multiproc.py).

Every faultpoint name is declared in :data:`REGISTERED_FAULTPOINTS`;
the no-dead-faultpoints hygiene gate (tests/test_code_hygiene.py)
fails any registered name no test exercises, and :func:`crashpoint` /
the io helpers refuse unregistered names loudly — a typo'd site must
not silently never fire.

Two gates keep this production-inert: the module is OFF unless
``YACY_FAULTS`` is set in the environment (parsed once at import) or a
test calls :func:`set_fault` explicitly, and every injection site
checks a single module flag before doing any work — the disabled cost
is one attribute read.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from collections import deque

_lock = threading.Lock()
_faults: dict[str, object] = {}
# fast-path gate: injection sites bail on this before touching the dict
_active = False

# schedule metadata (ISSUE 19): every arm/clear/expire is a timestamped
# event in a bounded ring, so the game-day conductor and the verdict
# engine join against ONE source of truth (wire-readable via
# do_meshfault?list=1) instead of parallel bookkeeping.  Monotonic per
# process — cross-process joins key on (pid, seq), never wall-clock
# ordering.
_schedule: deque = deque(maxlen=256)
_schedule_seq = 0

# every faultpoint name a production site may reach, with the site it
# lives at.  proc.crashpoint values (the named SIGKILL barriers) are
# listed in CRASHPOINTS below and are faultpoints in their own right
# for the hygiene gate.
REGISTERED_FAULTPOINTS = {
    "servlet.serving": "httpd dispatch latency inside the SLO wall",
    "batcher.dispatch": "forced dispatcher stall (worker_stall path)",
    "mesh.step": "mesh member step-execution latency (straggler "
                 "injection for the collective_straggler verdict)",
    "peer.blackhole": "RPCs to listed peer hashes fail",
    "proc.crashpoint": "named SIGKILL barrier (see CRASHPOINTS)",
    "io.torn_write": "durable write truncated at byte N, then raises",
    "io.error": "nth matching durable write raises OSError",
    "device.transfer_fail": "next N device transfers raise",
}

# the named kill−9 barriers inside the storage state machines.  Each is
# a REACHABLE site (crashpoint(name) in product code) and each must be
# exercised by the subprocess harness — the no-dead-faultpoints gate
# cross-references this tuple against tests/.
CRASHPOINTS = (
    # pagedrun.PagedRun.write: .dat renamed into place, .tix still .tmp
    "pagedrun.write.dat_renamed",
    # rwi.RWIIndex._swap_run: paged file pair on disk, manifest not yet
    # rewritten to reference it
    "rwi.flush.before_manifest",
    # rwi.RWIIndex._write_manifest: manifest .tmp written, not renamed
    "rwi.manifest.mid_write",
    # rwi.RWIIndex.merge_runs: merged run live in the manifest, victim
    # run files not yet unlinked
    "rwi.merge.before_unlink",
    # colstore.write_segment: payload partially written to .tmp
    "colstore.segment.mid_write",
    # metadata.MetadataStore._persist_state: new journal generation
    # created, manifest still names the old one
    "metadata.snapshot.before_manifest",
    # metadata.MetadataStore._persist_state: manifest switched, stale
    # segment/journal files not yet removed
    "metadata.snapshot.after_manifest",
)


class InjectedFault(Exception):
    """Raised by io.* and device.* faultpoints — typed so product code
    can treat an injected failure exactly like the real one while tests
    can still tell them apart in logs."""


def _parse_env() -> None:
    """``YACY_FAULTS="servlet.serving=250,peer.blackhole=abc:1.5"`` —
    comma-separated ``point=value`` pairs; blackhole values are
    ``hash[:delay_s]`` and may repeat."""
    spec = os.environ.get("YACY_FAULTS", "")
    for part in spec.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        if name == "peer.blackhole":
            h, _, delay = val.partition(":")
            blackhole_peer(h, float(delay) if delay else 0.0)
        else:
            try:
                set_fault(name, float(val))
            except ValueError:
                set_fault(name, val)


def _jsonable(value):
    return value if isinstance(value, (int, float, str, bool)) \
        or value is None else str(value)


def _note_event_locked(action: str, point: str, value=None) -> None:
    """Append one schedule event (caller holds _lock)."""
    global _schedule_seq
    _schedule_seq += 1
    _schedule.append({"seq": _schedule_seq,
                      "ts": round(time.time(), 3),
                      "action": action, "point": point,
                      "value": _jsonable(value),
                      "pid": os.getpid()})


def snapshot() -> dict:
    """The armed faults RIGHT NOW (JSON-safe values) — the flight
    recorder stamps this into every incident header so a post-hoc join
    reads which injections were live at dump time."""
    if not _active:
        return {}
    with _lock:
        return {k: _jsonable(v) for k, v in _faults.items()}


def schedule(n: int = 0) -> list[dict]:
    """The arm/clear/expire event history (newest last; `n` > 0 limits
    to the newest n) — the verdict engine's join key."""
    with _lock:
        evs = list(_schedule)
    return evs[-n:] if n > 0 else evs


def set_fault(name: str, value) -> None:
    """Arm one failpoint (tests; the env var feeds through here too)."""
    global _active
    base = name.split("=", 1)[0]
    if base not in REGISTERED_FAULTPOINTS:
        raise KeyError(f"unregistered faultpoint {name!r} — add it to "
                       "faultinject.REGISTERED_FAULTPOINTS")
    with _lock:
        _faults[name] = value
        _active = True
        _note_event_locked("arm", name, value)


def clear(name: str | None = None) -> None:
    """Disarm one failpoint, or all of them (test teardown)."""
    global _active
    with _lock:
        if name is None:
            for k in _faults:
                _note_event_locked("clear", k)
            _faults.clear()
        else:
            if name in _faults:
                _note_event_locked("clear", name)
            _faults.pop(name, None)
        _active = bool(_faults)


def get(name: str, default=None):
    if not _active:
        return default
    with _lock:
        return _faults.get(name, default)


def latency_ms(point: str) -> float:
    """Configured injected latency for a point (0.0 when unarmed)."""
    if not _active:
        return 0.0
    v = get(point, 0.0)
    try:
        return max(0.0, float(v))
    except (TypeError, ValueError):
        return 0.0


def sleep(point: str) -> float:
    """Injection site entry: sleep the configured latency (no-op when
    the point is unarmed); returns the ms slept."""
    if not _active:          # the production-path cost: one flag read
        return 0.0
    ms = latency_ms(point)
    if ms > 0.0:
        time.sleep(ms / 1000.0)
    return ms


# -- peer RPC blackhole ------------------------------------------------------

def blackhole_peer(peer_hash, delay_s: float = 0.0) -> None:
    """Arm the blackhole for one peer: RPCs to it fail after `delay_s`
    (0 = fail fast — the deterministic default for tests that assert
    the peer is SKIPPED, so an accidental call is loud, not slow)."""
    from .fleet import peer_key
    key = peer_key(peer_hash)
    holes = dict(get("peer.blackhole", {}) or {})
    holes[key] = float(delay_s)
    set_fault("peer.blackhole", holes)


def blackholed(peer_hash) -> bool:
    if not _active:
        return False
    from .fleet import peer_key
    key = peer_key(peer_hash)
    holes = get("peer.blackhole")
    return isinstance(holes, dict) and key in holes


def blackhole_delay_s(peer_hash) -> float:
    from .fleet import peer_key
    key = peer_key(peer_hash)
    holes = get("peer.blackhole")
    if not isinstance(holes, dict):
        return 0.0
    return float(holes.get(key, 0.0))


# -- crash barriers (ISSUE 10: the kill−9 chaos harness) ---------------------

def crashpoint(name: str) -> None:
    """Named SIGKILL barrier: when ``proc.crashpoint`` is armed with
    this name the process kills itself — no cleanup, no flush, the
    exact artifact a power-yanked node leaves behind.  Disabled cost:
    one module-flag read."""
    if not _active:
        return
    assert name in CRASHPOINTS, \
        f"unregistered crashpoint {name!r} — add it to CRASHPOINTS"
    armed = get("proc.crashpoint")
    if armed == name:
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)      # pragma: no cover — SIGKILL is not deferrable


def _match_path_spec(point: str, path: str):
    """Parse ``<frag>[:<n>]`` specs; returns the int suffix (default 1)
    when `path` contains the fragment, else None."""
    spec = get(point)
    if not isinstance(spec, str) or not spec:
        return None
    frag, _, n = spec.partition(":")
    if frag and frag in path:
        try:
            return int(n) if n else 1
        except ValueError:
            return 1
    return None


def torn_write_bytes(path: str):
    """``io.torn_write`` site: byte count to persist before the
    simulated crash-mid-write, or None when unarmed / non-matching.
    One-shot: the armed spec is consumed so recovery paths (the very
    thing under test) can write cleanly afterwards."""
    if not _active:
        return None
    n = _match_path_spec("io.torn_write", path)
    if n is not None:
        clear("io.torn_write")
    return n


def io_error(path: str) -> None:
    """``io.error`` site: the nth matching durable write raises.  The
    armed spec counts down; the failing occurrence consumes it."""
    if not _active:
        return
    with _lock:
        spec = _faults.get("io.error")
        if not isinstance(spec, str) or not spec:
            return
        frag, _, n = spec.partition(":")
        if not frag or frag not in path:
            return
        nth = int(n) if n else 1
        if nth > 1:
            _faults["io.error"] = f"{frag}:{nth - 1}"
            return
        _faults.pop("io.error", None)
        _note_event_locked("expired", "io.error")
    raise InjectedFault(f"injected io.error on {path}")


def take(point: str) -> bool:
    """Consume one charge of a COUNTED faultpoint (device.transfer_fail
    semantics: armed with N, the next N calls return True, then the
    point disarms itself — 'the device comes back')."""
    global _active
    if not _active:
        return False
    with _lock:
        v = _faults.get(point)
        if v is None:
            return False
        try:
            n = int(float(v))
        except (TypeError, ValueError):
            return False
        if n <= 0:
            _faults.pop(point, None)
            _active = bool(_faults)
            _note_event_locked("expired", point)
            return False
        if n == 1:
            # the counted point self-disarms ("the device comes back")
            # — a schedule event, so the verdict engine can see the
            # recovery edge even when no one ever called clear()
            _faults.pop(point, None)
            _active = bool(_faults)
            _note_event_locked("expired", point)
        else:
            _faults[point] = n - 1
        return True


_parse_env()
