"""Env-gated failpoints — deterministic fault injection for robustness
tests (ISSUE 9 satellite).

The self-defending serving loop (utils/actuator.py) only transitions on
REAL signals: a burn-rate rule firing, a batcher queue growing, a peer
digest reporting critical.  Testing those transitions organically means
sleeping until enough slow requests accumulate in 30 s histogram
windows — minutes per test.  Failpoints let a test drive the exact same
product code paths deterministically:

- ``servlet.serving`` latency injection: the httpd dispatch sleeps the
  configured milliseconds INSIDE the measured serving wall, so the SLO
  histogram fills with genuinely slow requests and the burn-rate rules
  fire on real data.
- ``batcher.dispatch`` forced worker_stall: a dispatcher sleeps inside
  its dispatch, so the watchdog's stall attribution and the
  worker_stall health rule see a real wedge.
- ``peer.blackhole``: RPCs to the listed peer hashes fail after an
  optional delay — the sick-peer avoidance path sees a genuinely
  unresponsive peer without a real network.

Two gates keep this production-inert: the module is OFF unless
``YACY_FAULTS`` is set in the environment (parsed once at import) or a
test calls :func:`set_fault` explicitly, and every injection site
checks a single module flag before doing any work — the disabled cost
is one attribute read.
"""

from __future__ import annotations

import os
import threading
import time

_lock = threading.Lock()
_faults: dict[str, object] = {}
# fast-path gate: injection sites bail on this before touching the dict
_active = False


def _parse_env() -> None:
    """``YACY_FAULTS="servlet.serving=250,peer.blackhole=abc:1.5"`` —
    comma-separated ``point=value`` pairs; blackhole values are
    ``hash[:delay_s]`` and may repeat."""
    spec = os.environ.get("YACY_FAULTS", "")
    for part in spec.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        if name == "peer.blackhole":
            h, _, delay = val.partition(":")
            blackhole_peer(h, float(delay) if delay else 0.0)
        else:
            try:
                set_fault(name, float(val))
            except ValueError:
                set_fault(name, val)


def set_fault(name: str, value) -> None:
    """Arm one failpoint (tests; the env var feeds through here too)."""
    global _active
    with _lock:
        _faults[name] = value
        _active = True


def clear(name: str | None = None) -> None:
    """Disarm one failpoint, or all of them (test teardown)."""
    global _active
    with _lock:
        if name is None:
            _faults.clear()
        else:
            _faults.pop(name, None)
        _active = bool(_faults)


def get(name: str, default=None):
    if not _active:
        return default
    with _lock:
        return _faults.get(name, default)


def latency_ms(point: str) -> float:
    """Configured injected latency for a point (0.0 when unarmed)."""
    if not _active:
        return 0.0
    v = get(point, 0.0)
    try:
        return max(0.0, float(v))
    except (TypeError, ValueError):
        return 0.0


def sleep(point: str) -> float:
    """Injection site entry: sleep the configured latency (no-op when
    the point is unarmed); returns the ms slept."""
    if not _active:          # the production-path cost: one flag read
        return 0.0
    ms = latency_ms(point)
    if ms > 0.0:
        time.sleep(ms / 1000.0)
    return ms


# -- peer RPC blackhole ------------------------------------------------------

def blackhole_peer(peer_hash, delay_s: float = 0.0) -> None:
    """Arm the blackhole for one peer: RPCs to it fail after `delay_s`
    (0 = fail fast — the deterministic default for tests that assert
    the peer is SKIPPED, so an accidental call is loud, not slow)."""
    from .fleet import peer_key
    key = peer_key(peer_hash)
    holes = dict(get("peer.blackhole", {}) or {})
    holes[key] = float(delay_s)
    set_fault("peer.blackhole", holes)


def blackholed(peer_hash) -> bool:
    if not _active:
        return False
    from .fleet import peer_key
    key = peer_key(peer_hash)
    holes = get("peer.blackhole")
    return isinstance(holes, dict) and key in holes


def blackhole_delay_s(peer_hash) -> float:
    from .fleet import peer_key
    key = peer_key(peer_hash)
    holes = get("peer.blackhole")
    if not isinstance(holes, dict):
        return 0.0
    return float(holes.get(key, 0.0))


_parse_env()
