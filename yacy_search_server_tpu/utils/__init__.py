"""Foundation substrate (cora-equivalent): orders, hashes, config, queues."""
