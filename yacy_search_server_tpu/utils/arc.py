"""ARC cache — recency + frequency segmented cache.

Capability equivalent of the reference's ARC family (reference:
source/net/yacy/cora/storage/SimpleARC.java / HashARC / ComparableARC /
ConcurrentARC — two-level caches where a hit in the recency level
promotes to the frequency level, each level LRU-bounded to half the
cache size; used for DNS, digest, and search-result caches). Backed by
ordered dicts; thread-safe.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable


class ARCCache:
    def __init__(self, max_size: int = 1024):
        self.level_size = max(1, max_size // 2)
        self._a: OrderedDict[Hashable, Any] = OrderedDict()  # recency
        self._b: OrderedDict[Hashable, Any] = OrderedDict()  # frequency
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def put(self, key: Hashable, value: Any) -> None:
        with self._lock:
            if key in self._b:
                self._b[key] = value
                self._b.move_to_end(key)
                return
            self._a[key] = value
            self._a.move_to_end(key)
            while len(self._a) > self.level_size:
                self._a.popitem(last=False)

    def get(self, key: Hashable, default: Any = None) -> Any:
        with self._lock:
            if key in self._b:
                self._b.move_to_end(key)
                self.hits += 1
                return self._b[key]
            if key in self._a:
                # second access: promote recency -> frequency
                value = self._a.pop(key)
                self._b[key] = value
                while len(self._b) > self.level_size:
                    self._b.popitem(last=False)
                self.hits += 1
                return value
            self.misses += 1
            return default

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._a or key in self._b

    def remove(self, key: Hashable) -> None:
        with self._lock:
            self._a.pop(key, None)
            self._b.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._a.clear()
            self._b.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._a) + len(self._b)
