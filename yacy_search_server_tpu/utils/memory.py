"""Memory governance — heap watermarks consulted by queues and caches.

Capability equivalent of the reference's memory governor (reference:
source/net/yacy/kelondro/util/MemoryControl.java:35,150): central place that
answers "is there room for this allocation" and "are we in short status",
so buffers flush and caches shed before the process OOMs. Here it watches
process RSS against a configurable budget (cgroup/system limits are read
when available).
"""

from __future__ import annotations

import gc
import os
import resource
import sys


def _read_int(path: str) -> int | None:
    try:
        with open(path, "r") as f:
            txt = f.read().strip()
        if txt == "max":
            return None
        return int(txt)
    except (OSError, ValueError):
        return None


def _detect_limit() -> int:
    # cgroup v2, then v1, then /proc/meminfo total
    for p in ("/sys/fs/cgroup/memory.max", "/sys/fs/cgroup/memory/memory.limit_in_bytes"):
        v = _read_int(p)
        if v and v < (1 << 50):
            return v
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 << 30


class _MemoryControl:
    def __init__(self):
        self.limit = _detect_limit()
        self.short_threshold = 0.9  # fraction of limit considered "short"
        # caches register shed hooks; request(force_flush=True) invokes them
        self._shed_hooks: list = []

    def register_shed_hook(self, hook) -> None:
        self._shed_hooks.append(hook)

    def used(self) -> int:
        """Current process RSS in bytes (peak RSS on non-/proc platforms)."""
        try:
            with open("/proc/self/statm") as f:
                pages = int(f.read().split()[1])
            return pages * os.sysconf("SC_PAGE_SIZE")
        except (OSError, ValueError):
            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # ru_maxrss is KiB on Linux but bytes on macOS
            return rss if sys.platform == "darwin" else rss * 1024

    def available(self) -> int:
        return max(0, self.limit - self.used())

    def short_status(self) -> bool:
        return self.used() > self.limit * self.short_threshold

    def request(self, size: int, force_flush: bool = False) -> bool:
        """True if `size` bytes can likely be allocated; with force_flush,
        shed registered caches and gc before giving up."""
        if self.available() >= size:
            return True
        if force_flush:
            for hook in self._shed_hooks:
                try:
                    hook()
                except Exception:
                    import logging
                    logging.getLogger("utils.memory").warning(
                        "load-shedding hook %r failed", hook, exc_info=True)
            gc.collect()
            return self.available() >= size
        return False


MemoryControl = _MemoryControl()
