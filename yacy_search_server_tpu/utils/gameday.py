"""Game day — the workload-realistic chaos conductor + incident→fault
attribution verdict engine (ISSUE 19, ROADMAP item 2).

The defense stack is proven piecewise (actuators, crash/device-loss
recovery, one-member-down survival, merge deferral) and M89 gave every
slow query exactly one attributed cause — but nothing yet proved the
observability stack *explains the right thing* when faults OVERLAP
under realistic load.  This module closes that loop with three layers:

- **Workload realism** — :class:`ZipfSampler` term popularity (a few
  head terms dominate, the tail is long), :class:`RateEnvelope`
  burst/diurnal phases (base load, a traffic spike, a quiet tail), and
  :class:`ClientPool` per-client identity shipped as X-Forwarded-For
  from the loopback generator — so the access tracker and the
  admission token buckets key on real client identities and actually
  engage (a denied client sees a counted 429 + Retry-After, never an
  error).
- **The chaos conductor** — :class:`Conductor` drives a scheduled set
  of OVERLAPPING :class:`ScheduledFault` windows against a live
  :class:`~..parallel.launcher.MeshFleet`, arming and clearing each
  fault cross-process through the ``do_meshfault`` wire (the same
  faultinject registry every robustness test uses; the member's own
  timestamped schedule — ``do_meshfault?list=1`` — is the shared
  source of truth).  While faults run, the conductor keeps issuing the
  zipfian workload, drives the coordinator's health engine, and
  snapshots the tail/scoreboard/conviction surfaces.
- **The verdict engine** — :class:`VerdictEngine` joins the
  machine-readable fault schedule against the flight-recorder incident
  stream (mesh member incidents + health incidents, both carrying
  ``incident_seq`` and the armed-fault snapshot), the
  ``yacy_tail_cause_total`` verdict stream and the straggler
  scoreboard, and renders one verdict row per scheduled fault:
  detected?  attributed to the RIGHT cause label and member?  bounded
  SLO recovery after clear?  100% answered during the fault (degraded
  + counted, never 500)?  bit-identical rankings after full recovery
  (the arxiv 1807.05798 tie discipline: the recovered fleet must rank
  EXACTLY as before)?

Scenario canon (:data:`SCHEDULABLE_FAULTS` / :func:`default_schedule`):
every conductor-schedulable fault has a detection contract — how its
incident must name it — and at least one scheduled window in the
default game day (the no-dead-schedulable-faults gate in
tests/test_gameday.py):

- ``mesh.step`` straggle during the traffic spike → dominant
  ``collective_straggler`` verdicts + the scoreboard (and a
  conviction) naming the slowed member, embedded in the SLO incident.
- ``device.transfer_fail`` (device loss) overlapping both neighbours →
  the coordinator's ``mesh_member_lost`` / ``mesh_member_recovered``
  incidents naming the member; queries degrade to the committed host
  answer, bit-identical, 100% answered.
- ``servlet.serving`` latency on the coordinator's regular dispatch →
  the ``slo_serving_p95`` incident whose armed-fault snapshot names
  the injected point.  (A fourth candidate — span corruption under a
  deferred merge — is not wire-schedulable against the frozen
  in-memory mesh corpus: there is no durable read path a remote arm
  could corrupt, so it stays with the crash-consistency harness.)

Jax-free by contract (the conductor talks HTTP to the fleet; the
verdict engine is pure joins), so ``bench.py --game-day`` and the
``Performance_GameDay_p`` servlet can import this from any process.
"""

from __future__ import annotations

import bisect
import random
import time
from dataclasses import dataclass, field

# the last completed run's result (the Performance_GameDay_p servlet
# serves this in-process view, falling back to the committed artifact)
LAST_RUN: dict | None = None

# every fault the conductor may schedule, with its detection contract —
# the verdict engine dispatches on `detect`, and the
# no-dead-schedulable-faults gate requires each point to carry at least
# one scheduled window in default_schedule()
SCHEDULABLE_FAULTS = {
    "mesh.step": {
        "detect": "tail",
        "expect_cause": "collective_straggler",
        "contract": "dominant collective_straggler verdicts + "
                    "scoreboard/conviction naming the slowed member",
    },
    "device.transfer_fail": {
        "detect": "mesh_incident",
        "expect_cause": "lost",
        "contract": "coordinator mesh_member_lost/_recovered incidents "
                    "naming the member; host-mode degraded answers",
    },
    "servlet.serving": {
        "detect": "slo_incident",
        "expect_cause": "servlet.serving",
        "contract": "slo_serving_p95 incident whose armed-fault "
                    "snapshot names the injected point",
    },
}


# -- workload realism --------------------------------------------------------

class ZipfSampler:
    """Seeded zipfian sampler over a fixed item list: weight of the
    rank-i item is 1/(i+1)^s — a few head terms dominate, the tail is
    long (the shape of real query logs)."""

    def __init__(self, items, s: float = 1.1, seed: int = 7):
        assert items, "zipf needs at least one item"
        self.items = list(items)
        self.s = float(s)
        self._rng = random.Random(seed)
        weights = [1.0 / (i + 1) ** self.s
                   for i in range(len(self.items))]
        total = sum(weights)
        self._cdf, acc = [], 0.0
        for w in weights:
            acc += w / total
            self._cdf.append(acc)
        self._cdf[-1] = 1.0

    def sample(self):
        return self.items[bisect.bisect_left(self._cdf,
                                             self._rng.random())]

    def weight(self, i: int) -> float:
        prev = self._cdf[i - 1] if i > 0 else 0.0
        return self._cdf[i] - prev


@dataclass
class Phase:
    """One piecewise-constant stretch of the rate envelope."""

    t: float                   # phase start, seconds from workload t0
    qps: float                 # mesh-query target rate
    name: str = "base"
    servlet_qps: float = 0.0   # regular-servlet GET side-load


class RateEnvelope:
    """Burst/diurnal rate envelope: piecewise-constant phases (base
    load → spike → quiet tail), queried by relative time."""

    def __init__(self, phases: list[Phase]):
        assert phases and phases[0].t <= 0.0, \
            "the envelope must cover t=0"
        self.phases = sorted(phases, key=lambda p: p.t)

    def at(self, t: float) -> Phase:
        cur = self.phases[0]
        for p in self.phases:
            if p.t <= t:
                cur = p
            else:
                break
        return cur

    def to_json(self) -> list[dict]:
        return [{"t": p.t, "name": p.name, "qps": p.qps,
                 "servlet_qps": p.servlet_qps} for p in self.phases]


class ClientPool:
    """Synthetic per-client identities (TEST-NET-3 addresses) with
    zipfian popularity: the hot client is what drains its token bucket
    while the tail clients stay admitted — per-client admission is the
    thing this exercises."""

    def __init__(self, n: int = 8, s: float = 1.1, seed: int = 11):
        self.clients = [f"203.0.113.{i + 1}" for i in range(n)]
        self._zipf = ZipfSampler(self.clients, s=s, seed=seed)

    def pick(self) -> str:
        return self._zipf.sample()


# -- the fault schedule ------------------------------------------------------

@dataclass
class ScheduledFault:
    """One fault window the conductor will arm and clear, plus the
    runtime bookkeeping the verdict engine joins on."""

    fault_id: str            # F1, F2, ... (stable row key)
    point: str               # faultinject registry name
    member: int              # target mesh process
    value: object            # armed value (ms, count, ...)
    t_arm: float             # planned, seconds from workload t0
    t_clear: float
    scenario: str = ""       # human-readable what/why
    # filled by the conductor:
    armed_ts: float = 0.0    # absolute wall time of the arm ack
    cleared_ts: float = 0.0
    arm_ack: dict = field(default_factory=dict)
    clear_ack: dict = field(default_factory=dict)

    def detect(self) -> str:
        return SCHEDULABLE_FAULTS[self.point]["detect"]

    def row(self) -> dict:
        return {"fault_id": self.fault_id, "point": self.point,
                "member": self.member, "target": f"mesh{self.member}",
                "value": self.value if isinstance(
                    self.value, (int, float, str)) else str(self.value),
                "t_arm": self.t_arm, "t_clear": self.t_clear,
                "armed_ts": round(self.armed_ts, 3),
                "cleared_ts": round(self.cleared_ts, 3),
                "scenario": self.scenario,
                "detect": self.detect(),
                "expect_cause":
                    SCHEDULABLE_FAULTS[self.point]["expect_cause"],
                "arm_ack": self.arm_ack, "clear_ack": self.clear_ack}


def default_schedule(straggle_ms: float = 250.0,
                     servlet_ms: float = 300.0,
                     scale: float = 1.0) -> list[ScheduledFault]:
    """The default game day: three overlapping fault windows (F2
    overlaps both F1 and F3).  `scale` compresses the timeline for
    smoke runs."""
    def t(x):
        return round(x * scale, 1)
    return [
        ScheduledFault(
            "F1", "mesh.step", 1, straggle_ms, t(10), t(48),
            scenario="straggling mesh member during the traffic "
                     "spike (zipf head terms, burst envelope)"),
        ScheduledFault(
            "F2", "device.transfer_fail", 2, 100000, t(35), t(140),
            scenario="device loss in one member while the straggle "
                     "is still live, held across the servlet fault "
                     "(overlaps F1 and F3)"),
        ScheduledFault(
            "F3", "servlet.serving", 0, servlet_ms, t(130), t(170),
            scenario="coordinator servlet-dispatch latency under "
                     "regular-servlet side-load while the fleet is "
                     "still in degraded host mode"),
    ]


def default_envelope(scale: float = 1.0) -> RateEnvelope:
    """Base load → spike (over F1) → sustained base with a regular-
    servlet side-load bracketing F3 → quiet tail for recovery
    evidence."""
    def t(x):
        return round(x * scale, 1)
    return RateEnvelope([
        Phase(0.0, 2.5, "base"),
        Phase(t(8), 5.0, "spike"),
        Phase(t(50), 2.5, "base"),
        Phase(t(100), 2.0, "servlet-burst", servlet_qps=2.0),
        Phase(t(180), 1.5, "recovery-tail"),
    ])


# -- the verdict engine ------------------------------------------------------

def _dominant(causes: dict) -> str:
    if not causes:
        return ""
    best = max(causes, key=lambda c: causes[c])
    return best if causes[best] > 0 else ""


class VerdictEngine:
    """Pure joins: the fault schedule × the incident streams × the
    tail-cause/scoreboard windows × the query log → one verdict row
    per scheduled fault.  No wall-clock ordering assumptions across
    processes: incidents are matched by window + (pid, incident_seq)
    identity, never by sort order."""

    def __init__(self, schedule: list[ScheduledFault], evidence: dict,
                 grace_s: float = 25.0, recovery_bound_s: float = 60.0):
        self.schedule = schedule
        self.ev = evidence
        self.grace_s = grace_s
        self.recovery_bound_s = recovery_bound_s

    # -- per-gate judges -----------------------------------------------------

    def _in_window(self, ts: float, f: ScheduledFault,
                   grace: float | None = None) -> bool:
        g = self.grace_s if grace is None else grace
        return f.armed_ts - 2.0 <= ts <= f.cleared_ts + g

    def _judge_tail(self, f: ScheduledFault) -> tuple[bool, bool, dict]:
        """mesh.step: the verdict stream must carry
        collective_straggler rows NAMING the member, the windowed cause
        histogram must be dominated by it while the fault is live, and
        the scoreboard/conviction must convict the same member."""
        want = SCHEDULABLE_FAULTS[f.point]["expect_cause"]
        target = f"mesh{f.member}"
        named = [v for v in self.ev.get("tail_verdicts", [])
                 if v.get("cause") == want
                 and self._in_window(v.get("ts", 0.0), f)]
        member_ok = any(v.get("member") == target for v in named)
        dominant, board_top = "", ""
        for p in self.ev.get("probes", []):
            if not self._in_window(p.get("ts", 0.0), f, grace=5.0):
                continue
            d = _dominant(p.get("causes", {}))
            if d:
                dominant = d
            rows = p.get("scoreboard", [])
            if rows:
                top = max(rows, key=lambda r: r.get("slowest_frac", 0))
                if top.get("slowest_frac", 0) > 0:
                    board_top = top.get("member", "")
        convictions = self.ev.get("convictions", {})
        evidence = {
            "straggler_verdicts_in_window": len(named),
            "named_member_ok": member_ok,
            "dominant_cause_in_window": dominant,
            "scoreboard_top_in_window": board_top,
            "convictions": convictions.get(target, 0)}
        detected = bool(named)
        attributed = (member_ok and dominant == want
                      and board_top == target)
        return detected, attributed, evidence

    def _judge_mesh_incident(self, f: ScheduledFault
                             ) -> tuple[bool, bool, dict]:
        """device.transfer_fail: the coordinator's flight recorder must
        carry mesh_member_lost naming the member inside the window and
        mesh_member_recovered after the clear."""
        target = f"mesh{f.member}"
        incs = self.ev.get("mesh_incidents", [])
        lost = [i for i in incs if i.get("name") == "mesh_member_lost"
                and i.get("member") == target
                and self._in_window(i.get("ts", 0.0), f)]
        recovered = [i for i in incs
                     if i.get("name") == "mesh_member_recovered"
                     and i.get("member") == target
                     and i.get("ts", 0.0) >= f.cleared_ts - 2.0]
        evidence = {
            "lost_incidents": [{"seq": i.get("incident_seq"),
                                "ts": i.get("ts"),
                                "cause": i.get("cause")} for i in lost],
            "recovered_incidents": len(recovered)}
        detected = bool(lost)
        attributed = detected and bool(recovered) \
            and all(i.get("cause") == "lost" for i in lost)
        return detected, attributed, evidence

    def _judge_slo_incident(self, f: ScheduledFault
                            ) -> tuple[bool, bool, dict]:
        """servlet.serving: a health incident must fire inside the
        window with an SLO rule critical AND its armed-fault snapshot
        naming the injected point — the join that makes 'p95 burning'
        read 'p95 burning because servlet.serving=300 was armed'."""
        hits = []
        for i in self.ev.get("health_incidents", []):
            if not self._in_window(i.get("ts", 0.0), f):
                continue
            if not any("slo" in r for r in i.get("rules", [])):
                continue
            armed = i.get("armed_faults", {}) or {}
            hits.append({"seq": i.get("seq"), "ts": i.get("ts"),
                         "rules": i.get("rules"),
                         "names_point": f.point in armed,
                         "armed": armed})
        evidence = {"slo_incidents_in_window": hits}
        detected = bool(hits)
        attributed = any(h["names_point"] for h in hits)
        return detected, attributed, evidence

    def _judge_answered(self, f: ScheduledFault) -> tuple[bool, dict]:
        """100% answered while the fault is live: every workload
        request got an HTTP answer — 200 (full or degraded) or a
        counted 429 with Retry-After — never a 5xx, never a hang."""
        total = ok = degraded = errors = 0
        for q in self.ev.get("queries", []):
            if not (f.armed_ts <= q.get("ts", 0.0) <= f.cleared_ts):
                continue
            total += 1
            st = q.get("status", 0)
            if st == 200:
                ok += 1
            elif st == 429:
                degraded += 1
            else:
                errors += 1
        return (total > 0 and errors == 0), {
            "in_window": total, "ok_200": ok, "degraded_429": degraded,
            "errors": errors}

    def _judge_recovery(self, f: ScheduledFault) -> tuple[bool, dict]:
        """Bounded SLO recovery: after the clear, the workload's own
        walls must come back under the bound within recovery_bound_s
        (3 consecutive under-bound requests of the fault's kind mark
        the recovery point)."""
        kind = "servlet" if f.point == "servlet.serving" else "mesh"
        base = self.ev.get("baseline_ms", {}).get(kind, 50.0)
        bound_ms = max(250.0, 3.0 * base)
        walls = [(q["ts"], q.get("dur_ms", 0.0))
                 for q in self.ev.get("queries", [])
                 if q.get("kind") == kind and q.get("status") == 200
                 and q.get("ts", 0.0) >= f.cleared_ts]
        recovered_s = None
        for i in range(len(walls)):
            run = walls[i:i + 3]
            # a FULL window only: a 1-2 sample tail slice must not let
            # one lucky fast request mark the recovery point
            if len(run) == 3 and all(w <= bound_ms for _, w in run):
                recovered_s = walls[i][0] - f.cleared_ts
                break
        ok = recovered_s is not None \
            and recovered_s <= self.recovery_bound_s
        return ok, {"bound_ms": round(bound_ms, 1),
                    "recovery_bound_s": self.recovery_bound_s,
                    "recovered_s": (round(recovered_s, 2)
                                    if recovered_s is not None
                                    else None),
                    "post_clear_samples": len(walls)}

    # -- the table -----------------------------------------------------------

    def verdicts(self) -> list[dict]:
        judges = {"tail": self._judge_tail,
                  "mesh_incident": self._judge_mesh_incident,
                  "slo_incident": self._judge_slo_incident}
        bit = self.ev.get("bit_identity", {})
        rows = []
        for f in self.schedule:
            detected, attributed, evidence = judges[f.detect()](f)
            answered, answered_ev = self._judge_answered(f)
            recovered, recovery_ev = self._judge_recovery(f)
            bit_ok = bool(bit.get("identical"))
            gates = {"detected": detected, "attributed": attributed,
                     "answered": answered, "slo_recovery": recovered,
                     "bit_identical": bit_ok}
            failed = [g for g, ok in gates.items() if not ok]
            rows.append({**f.row(), **gates,
                         "evidence": evidence,
                         "answered_detail": answered_ev,
                         "recovery": recovery_ev,
                         "verdict": "pass" if not failed
                         else "fail:" + "+".join(failed)})
        return rows


# -- the conductor -----------------------------------------------------------

class Conductor:
    """Drives one game day against a live MeshFleet: the zipfian
    workload under the rate envelope with per-client identity, the
    fault schedule armed/cleared over the wire, periodic health ticks
    + evidence snapshots, then the post-run recovery wait, the
    bit-identity probe and the verdict join."""

    def __init__(self, fleet, schedule: list[ScheduledFault],
                 terms: list[str], envelope: RateEnvelope,
                 duration_s: float, clients: ClientPool | None = None,
                 zipf_s: float = 1.1, probe_every_s: float = 5.0,
                 servlet_page: str = "Status.html",
                 recovery_bound_s: float = 60.0, k: int = 10):
        self.fleet = fleet
        self.schedule = schedule
        self.terms = list(terms)
        self.envelope = envelope
        self.duration_s = float(duration_s)
        self.clients = clients or ClientPool()
        self.zipf = ZipfSampler(self.terms, s=zipf_s, seed=7)
        self.probe_every_s = probe_every_s
        self.servlet_page = servlet_page
        self.recovery_bound_s = recovery_bound_s
        self.k = k
        self.queries: list[dict] = []
        self.probes: list[dict] = []
        # the wire info() view exposes only the newest few verdicts, so
        # the conductor accumulates the union across probes (keyed by
        # trace id) — F1-window evidence must survive to the final join
        self.tail_verdicts: dict[str, dict] = {}
        self.baseline: dict[str, dict] = {}
        self.baseline_ms: dict[str, float] = {}

    # -- pieces --------------------------------------------------------------

    def warm_and_baseline(self) -> None:
        """Compile-warm every term's shapes, then pin the pre-fault
        reference rankings (loopback identity — the baseline and the
        final bit-identity probe must never be admission-denied)."""
        walls = []
        for _ in range(2):
            for w in self.terms:
                t0 = time.perf_counter()
                rep = self.fleet.search(w, k=self.k)
                walls.append((time.perf_counter() - t0) * 1000.0)
                assert rep.get("scores") is not None, rep
        for w in self.terms:
            rep = self.fleet.search(w, k=self.k)
            assert rep["mode"] == "collective", (
                f"baseline must be collective, got {rep['mode']}")
            self.baseline[w] = {"scores": rep["scores"],
                                "docids": rep["docids"]}
        walls.sort()
        self.baseline_ms["mesh"] = walls[len(walls) // 2]
        st, wall = self.fleet.get(0, self.servlet_page)
        assert st == 200, f"servlet baseline GET failed: {st}"
        self.baseline_ms["servlet"] = wall
        # warmup/measurement boundary: drop the windowed histogram
        # samples recorded so far — the compile-era warmup walls are
        # orders of magnitude above the live workload and would hold
        # the classifier's cached-p95 exemplar gate above every
        # fault-slowed query for WINDOWS*30s.  The workload starts
        # against the `tail.minMs` floor and the gate re-learns from
        # live windows only.
        self.fleet.info(0, prime_tail_gate=True)

    def _fire_due(self, t: float) -> None:
        for f in self.schedule:
            if f.armed_ts == 0.0 and t >= f.t_arm:
                f.arm_ack = self.fleet.fault(f.member, f.point, f.value)
                f.armed_ts = time.time()
                assert f.arm_ack.get("result") == "ok", (f, f.arm_ack)
            elif f.armed_ts and f.cleared_ts == 0.0 \
                    and t >= f.t_clear:
                f.clear_ack = self.fleet.fault(f.member, f.point, None,
                                               clear=True)
                f.cleared_ts = time.time()
                assert f.clear_ack.get("result") == "ok", \
                    (f, f.clear_ack)

    def _probe(self, t: float) -> None:
        info = self.fleet.info(0, tick_health=True)
        tail = info.get("tail", {})
        for v in tail.get("verdicts", []):
            self.tail_verdicts[v.get("trace_id", str(v.get("ts")))] = v
        self.probes.append({
            "t": round(t, 2), "ts": time.time(),
            "causes": tail.get("causes", {}),
            "scoreboard": tail.get("scoreboard", []),
            "convictions": tail.get("convictions", {}),
            "health_incidents": len(info.get("health_incidents", [])),
            "mesh_incidents": len(info.get("incidents", []))})

    def _one_query(self, t: float) -> None:
        term = self.zipf.sample()
        client = self.clients.pick()
        t0 = time.perf_counter()
        try:
            status, rep = self.fleet.search_ex(term, k=self.k,
                                               client=client)
        except Exception as e:   # transport failure = NOT answered
            status, rep = -1, {"error": repr(e)}
        self.queries.append({
            "t": round(t, 2), "ts": time.time(), "kind": "mesh",
            "term": term, "client": client, "status": status,
            "mode": rep.get("mode", ""),
            "dur_ms": round((time.perf_counter() - t0) * 1000.0, 2)})

    def _one_get(self, t: float) -> None:
        client = self.clients.pick()
        try:
            status, wall = self.fleet.get(0, self.servlet_page,
                                          client=client)
        except Exception as e:
            status, wall = -1, 0.0
        self.queries.append({
            "t": round(t, 2), "ts": time.time(), "kind": "servlet",
            "page": self.servlet_page, "client": client,
            "status": status, "dur_ms": round(wall, 2)})

    def run_workload(self) -> None:
        t0 = time.monotonic()
        next_mesh = next_servlet = 0.0
        next_probe = self.probe_every_s
        while True:
            t = time.monotonic() - t0
            if t >= self.duration_s:
                break
            self._fire_due(t)
            if t >= next_probe:
                self._probe(t)
                next_probe = t + self.probe_every_s
            ph = self.envelope.at(t)
            did = False
            if t >= next_mesh:
                self._one_query(t)
                gap = 1.0 / max(0.1, ph.qps)
                # bounded catch-up: a straggled query may owe several
                # ticks; burst at most 2 gaps behind real time (a real
                # client retries, it does not replay its whole backlog)
                next_mesh = max(next_mesh + gap,
                                time.monotonic() - t0 - 2 * gap)
                did = True
            if ph.servlet_qps > 0 and t >= next_servlet:
                self._one_get(t)
                sgap = 1.0 / ph.servlet_qps
                next_servlet = max(next_servlet + sgap,
                                   time.monotonic() - t0 - 2 * sgap)
                did = True
            if not did:
                wake = min(next_mesh, next_probe,
                           next_servlet if ph.servlet_qps > 0
                           else next_mesh)
                time.sleep(min(0.05, max(0.005,
                                         wake - (time.monotonic()
                                                 - t0))))
        # anything still armed clears at the horizon (the schedule is
        # the contract: the run ends with every fault cleared); twice,
        # so a window the loop never reached arms and then clears
        self._fire_due(self.duration_s + 1e9)
        self._fire_due(self.duration_s + 1e9)

    def wait_full_recovery(self, timeout_s: float = 120.0) -> dict:
        """After every clear: wait for lost members to rebuild and for
        collectives to resume — the precondition of the bit-identity
        probe (host answers are bit-identical too, but the acceptance
        gate is the RECOVERED fleet ranking exactly as before)."""
        out = {"lost_cleared": {}, "collective_resumed": False,
               "wall_s": 0.0}
        t0 = time.monotonic()
        lost_members = {f.member for f in self.schedule
                        if f.point == "device.transfer_fail"}
        for m in sorted(lost_members):
            while time.monotonic() - t0 < timeout_s:
                if not self.fleet.info(m).get("lost"):
                    out["lost_cleared"][f"mesh{m}"] = True
                    break
                time.sleep(0.5)
            else:
                out["lost_cleared"][f"mesh{m}"] = False
        while time.monotonic() - t0 < timeout_s:
            rep = self.fleet.search(self.terms[0], k=self.k)
            if rep.get("mode") == "collective":
                out["collective_resumed"] = True
                break
            time.sleep(0.5)
        out["wall_s"] = round(time.monotonic() - t0, 2)
        return out

    def bit_identity_probe(self) -> dict:
        """Re-rank every term on the recovered fleet and compare
        bit-for-bit against the pre-fault baseline."""
        per_term, identical = {}, True
        for w in self.terms:
            rep = self.fleet.search(w, k=self.k)
            same = (rep["scores"] == self.baseline[w]["scores"]
                    and rep["docids"] == self.baseline[w]["docids"])
            per_term[w] = {"identical": same, "mode": rep["mode"]}
            identical = identical and same
        return {"identical": identical, "terms": per_term}

    # -- the whole day -------------------------------------------------------

    def run(self) -> dict:
        global LAST_RUN
        self.warm_and_baseline()
        self.run_workload()
        recovery = self.wait_full_recovery()
        bit = self.bit_identity_probe()
        info = self.fleet.info(0, tick_health=True)
        tail = info.get("tail", {})
        for v in tail.get("verdicts", []):
            self.tail_verdicts[v.get("trace_id", str(v.get("ts")))] = v
        all_verdicts = sorted(self.tail_verdicts.values(),
                              key=lambda v: v.get("ts", 0.0))
        evidence = {
            "queries": self.queries,
            "probes": self.probes,
            "tail_verdicts": all_verdicts,
            "mesh_incidents": info.get("incidents", []),
            "health_incidents": info.get("health_incidents", []),
            "convictions": tail.get("convictions", {}),
            "bit_identity": bit,
            "baseline_ms": self.baseline_ms,
        }
        rows = VerdictEngine(
            self.schedule, evidence,
            recovery_bound_s=self.recovery_bound_s).verdicts()
        statuses: dict[str, int] = {}
        for q in self.queries:
            key = str(q["status"])
            statuses[key] = statuses.get(key, 0) + 1
        mesh_q = [q for q in self.queries if q["kind"] == "mesh"]
        # the ISSUE 19 gate is zero unattributed verdicts UNDER THE
        # SCHEDULED FAULTS: every tail query inside an armed window
        # must name its injected cause.  Outside the windows a
        # CPU-contended environment can legitimately produce slow-but-
        # uniform queries with nothing to attribute; the run-wide
        # cumulative count stays in the artifact (unattributed_total)
        # for diagnosability but does not gate.
        def _in_fault_window(ts: float) -> bool:
            return any(f.armed_ts <= ts <= f.cleared_ts
                       for f in self.schedule)
        unattr_all = [v for v in all_verdicts
                      if v.get("cause") == "unattributed"]
        unattr_in_window = [v for v in unattr_all
                            if _in_fault_window(v.get("ts", 0.0))]
        result = {
            "bench": "game_day",
            "workload": {
                "terms": self.terms,
                "zipf_s": self.zipf.s,
                "clients": self.clients.clients,
                "phases": self.envelope.to_json(),
                "duration_s": self.duration_s,
                "queries_total": len(self.queries),
                "mesh_queries": len(mesh_q),
                "servlet_gets": len(self.queries) - len(mesh_q),
                "by_status": statuses,
                "baseline_ms": {k: round(v, 2) for k, v
                                in self.baseline_ms.items()},
            },
            "schedule": rows,
            "overlaps": self._overlaps(),
            "verdict_summary": {
                "faults": len(rows),
                "passed": sum(1 for r in rows
                              if r["verdict"] == "pass"),
                "all_pass": all(r["verdict"] == "pass" for r in rows),
                "unattributed_verdicts": len(unattr_in_window),
                "unattributed_total": int(
                    tail.get("cause_totals", {})
                    .get("unattributed", 0)),
                # any unattributed verdict the probes caught, verbatim
                # (in-window ones first) — the zero-unattributed gate
                # must be diagnosable from the artifact alone when it
                # trips
                "unattributed_sample":
                    (unattr_in_window or unattr_all)[:10],
                "never_500": all(200 <= q["status"] < 500
                                 for q in self.queries),
            },
            "tail": {
                "cause_totals": tail.get("cause_totals", {}),
                "stragglers": tail.get("stragglers", {}),
                "scoreboard": tail.get("scoreboard", []),
                "convictions": tail.get("convictions", {}),
                "conviction_crumbs": tail.get("conviction_crumbs", []),
            },
            "incidents": {
                "mesh": info.get("incidents", []),
                "health": info.get("health_incidents", []),
            },
            "fault_wire_schedule": {
                f"mesh{i}": self.fleet.fault_list(i).get("schedule", [])
                for i in range(self.fleet.procs)
            },
            "recovery": recovery,
            "bit_identity": bit,
        }
        LAST_RUN = result
        return result

    def _overlaps(self) -> list[list[str]]:
        out = []
        sched = sorted(self.schedule, key=lambda f: f.t_arm)
        for i, a in enumerate(sched):
            for b in sched[i + 1:]:
                if b.t_arm < a.t_clear and a.t_arm < b.t_clear:
                    out.append([a.fault_id, b.fault_id])
        return out
