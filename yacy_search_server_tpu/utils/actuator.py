"""Self-defending serving — the actuator layer that closes the loop.

Five rounds of observability (roofline → tracing → histograms → health
rules → fleet gossip) built a node that can *diagnose* itself in detail
and *do* nothing about it: the burn-rate rules page, the queues grow,
the sick peer drags every global query, and a human is still the only
actuator.  ROADMAP item 3: at millions of users the rules must defend
the serving SLO themselves.  This module is the decision half of that
loop, with the same declarative discipline as `utils/health.py` rules
(ISSUE 9 tentpole):

- Each :class:`Actuator` pins the exact `/metrics` series it reads, the
  config knob it writes, and an ``evaluate`` that maps the current
  signals to a bounded state change.  A state change emits a
  flight-recorder breadcrumb (dumped inside health incidents) and bumps
  ``yacy_actuator_transitions_total{actuator,dir}`` — every actuation
  is attributable after the fact, and the no-dead-actuators hygiene
  gate (`undefined_series`) fails any actuator referencing a series the
  exposition does not serve.  Knob semantics: ``index.device.*`` is a
  REAL config knob (re-read at switchboard init, so tuning persists a
  restart); ``serving.degradeLevel`` and ``remotesearch.avoidPeers``
  are write-only operator-visible mirrors — the live serving path
  reads the engine (`effective_level()` / `avoided_peers()`), never
  the config, so a restart always comes up at full service with an
  empty avoid set.
- **serving_ladder** — the degradation ladder, driven by the
  ``slo_serving_p95`` burn-rate state: full → skip live snippets →
  skip dense rerank → rank-cache/stale-ok only → shed with a computed
  ``Retry-After``.  One rung DOWN per sustained-burn tick, one rung UP
  only after ``actuator.recoverTicks`` consecutive healthy ticks
  (hysteresis: a flapping rule must not oscillate the serving mode).
  Every degraded answer stays deterministically ordered: each rung
  serves exactly a prefix of the full pipeline's stages, whose tie
  discipline (score DESC, docid ASC) is already pinned per stage
  (arxiv 1807.05798 — ties that flap across serving modes defeat the
  versioned top-k cache and surface as result churn).
- **batcher_autotune** — adapts the dispatcher count and completer
  depth of the live batcher (`devstore._QueryBatcher` /
  `meshstore._MeshQueryBatcher`) within configured bounds from the same
  queue-depth gauges the backlog rule reads.  Bounded step-per-window:
  at most ±1 per tick, and only on a `recoverTicks`-sustained signal —
  a healthy soak must show ZERO transitions (the bench gate).  The
  floor (1 dispatcher, depth 1) can never deadlock the pipeline.
- **remote_peer_guard** — writes the ``remotesearch.avoidPeers`` knob
  from the fleet table's digest-reported health: peers reporting
  critical (or a leave-one-out serving-p95 outlier) are skipped by the
  scatter until their digests recover, so one sick peer stops dragging
  every global query.

Admission control (the per-client token buckets `server/httpd.py`
consults, layered on `accesstracker.track_access` host accounting)
lives here too: the bucket's refill time is what turns the hard-coded
``Retry-After: 600`` into an honest number.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

# ladder rungs (serving.degradeLevel): each rung serves a PREFIX of the
# full pipeline, so degraded answers are bit-identical in ordering to
# the corresponding non-degraded stage outputs
LEVEL_FULL = 0                  # everything: snippets, rerank, device
LEVEL_NO_LIVE_SNIPPETS = 1      # skip live snippet fetches (cache-local only)
LEVEL_NO_RERANK = 2             # skip the dense rerank stage (sparse order)
LEVEL_CACHE_ONLY = 3            # serve the rank cache (stale-ok); miss = empty
LEVEL_SHED = 4                  # shed search requests with Retry-After

# dense-first candidate generation (ISSUE 11) sheds at rung 1 — ONE
# rung BEFORE the rerank: the ANN probe is the more expensive dense
# stage, and shedding it still serves a full hybrid (sparse + rerank)
# answer.  An alias of the snippet rung, not a new rung: the ladder's
# metric/name surface (LEVEL_NAMES, zero-filled series) is unchanged.
LEVEL_NO_DENSE_FIRST = LEVEL_NO_LIVE_SNIPPETS

LEVEL_NAMES = ("full", "no_live_snippets", "no_rerank", "cache_only",
               "shed")
N_LEVELS = len(LEVEL_NAMES)


class TokenBucketTable:
    """Per-client token buckets for admission control — EXACT under one
    lock (the 32-thread exactness test pins it): with refill disabled,
    precisely ``capacity`` acquires succeed per client no matter the
    thread count.  `acquire` returns the refill-derived ``Retry-After``
    on denial, which is what replaces httpd's hard-coded 600."""

    def __init__(self, capacity: float, refill_per_s: float,
                 max_clients: int = 20_000):
        self.capacity = float(max(1.0, capacity))
        self.refill_per_s = float(max(0.0, refill_per_s))
        self.max_clients = max_clients
        self._lock = threading.Lock()
        # client -> [tokens, last_refill_monotonic]
        self._buckets: dict[str, list] = {}
        self._calls = 0
        self.denied = 0

    def acquire(self, client: str, cost: float = 1.0,
                now: float | None = None) -> tuple[bool, float]:
        """Take `cost` tokens; returns (allowed, retry_after_s) where
        retry_after_s is the time until the bucket refills enough for
        one more request (0.0 when allowed)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            b = self._buckets.get(client)
            if b is None:
                b = self._buckets[client] = [self.capacity, now]
                self._calls += 1
                if len(self._buckets) > self.max_clients:
                    self._prune_locked(now, keep=client)
            tokens, last = b
            tokens = min(self.capacity,
                         tokens + (now - last) * self.refill_per_s)
            if tokens >= cost:
                b[0], b[1] = tokens - cost, now
                return True, 0.0
            b[0], b[1] = tokens, now
            self.denied += 1
            if self.refill_per_s <= 0.0:
                return False, 600.0          # no refill: the legacy cap
            return False, max(1.0, (cost - tokens) / self.refill_per_s)

    def refill_eta(self, client: str, cost: float = 1.0,
                   now: float | None = None) -> float:
        """Time until `client` could pass one request, WITHOUT charging
        the bucket — the honest Retry-After for denials decided by
        other policies (httpd's legacy windowed host count)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            b = self._buckets.get(client)
            if b is None:
                return 1.0
            tokens = min(self.capacity,
                         b[0] + (now - b[1]) * self.refill_per_s)
            if tokens >= cost:
                return 1.0
            if self.refill_per_s <= 0.0:
                return 600.0
            return max(1.0, (cost - tokens) / self.refill_per_s)

    def _prune_locked(self, now: float, keep: str | None = None) -> None:
        """Bound the table: drop refilled-to-capacity buckets (idle
        clients), and if a unique-IP spray keeps every bucket non-full,
        force-evict the FULLEST ones down to 90% of the cap — an
        evicted client returns with a fresh full bucket, so eviction
        can only ever be generous, never a lockout; the 10% slack
        amortizes the scan instead of re-running it per new client.
        `keep` is the caller whose just-created (full) bucket triggered
        the prune: evicting it would orphan the spend acquire() is
        about to write."""
        full = [c for c, (t, last) in self._buckets.items()
                if c != keep
                and t + (now - last) * self.refill_per_s
                >= self.capacity - 1e-9]
        for c in full:
            del self._buckets[c]
        excess = len(self._buckets) - int(self.max_clients * 0.9)
        if excess > 0:
            victims = sorted(
                ((c, b) for c, b in self._buckets.items() if c != keep),
                key=lambda kv: -(kv[1][0]
                                 + (now - kv[1][1]) * self.refill_per_s)
            )[:excess]
            for c, _b in victims:
                del self._buckets[c]

    def __len__(self) -> int:
        with self._lock:
            return len(self._buckets)


@dataclass(frozen=True)
class Actuator:
    """One closed-loop controller: `series` lists every exposition
    sample the evaluator reads (the no-dead-actuators hygiene
    contract), `knob` names the config key it writes, `evaluate` maps
    the engine's current signals to a transition dict or None."""

    name: str
    description: str
    series: tuple
    knob: str
    evaluate: Callable


def build_actuators(cfg) -> list:
    """The three controllers (thresholds read once at build time, like
    `health.build_rules`)."""
    recover_ticks = max(1, cfg.get_int("actuator.recoverTicks", 3))
    max_level = min(LEVEL_SHED, cfg.get_int("actuator.maxDegradeLevel",
                                            LEVEL_SHED))
    disp_min = max(1, cfg.get_int("actuator.dispatcherMin", 2))
    disp_max = max(disp_min, cfg.get_int("actuator.dispatcherMax", 16))
    depth_min = max(1, cfg.get_int("actuator.completerDepthMin", 1))
    depth_max = max(depth_min, cfg.get_int("actuator.completerDepthMax",
                                           4))
    backlog_factor = cfg.get_float("actuator.backlogFactor", 2.0)
    # same thresholds as the fleet_peer_outlier RULE: the actuation must
    # never avoid a peer the diagnostic layer would refuse to judge
    outlier_factor = cfg.get_float("health.fleetOutlierFactor", 3.0)
    outlier_min_mesh = cfg.get_int("health.fleetOutlierMinSamples", 50)
    outlier_min_peer = cfg.get_int("health.fleetOutlierMinPeerSamples",
                                   20)

    def serving_ladder(eng: "ActuatorEngine"):
        st = eng.rule_state("slo_serving_p95")
        old = eng.level
        new = old
        if st == "critical":
            eng._ok_streak = 0
            new = min(max_level, old + 1)
        elif st == "ok":
            eng._ok_streak += 1
            if eng._ok_streak >= recover_ticks and old > 0:
                eng._ok_streak = 0
                new = old - 1
        else:                       # warn (or unknown): hold the rung
            eng._ok_streak = 0
        if new == old:
            return None
        eng.level = new
        eng.sb.config.set("serving.degradeLevel", new)
        return {
            "dir": "down" if new > old else "up",
            "from": LEVEL_NAMES[old], "to": LEVEL_NAMES[new],
            "cause": (f"slo_serving_p95 {st}: ladder "
                      f"{LEVEL_NAMES[old]} -> {LEVEL_NAMES[new]}"),
            "evidence": {"rule_state": st, "level": new,
                         "ok_streak": eng._ok_streak},
        }

    def batcher_autotune(eng: "ActuatorEngine"):
        b = eng._live_batcher()
        if b is None or not hasattr(b, "set_tuning"):
            return None
        tun = b.tuning()
        disp, depth = tun["dispatchers"], tun["completer_depth"]
        qdepth = tun["queue_incoming"] + tun["queue_inflight"]
        dispatches = tun["dispatches"]
        busy = dispatches > eng._last_dispatches
        eng._last_dispatches = dispatches
        # sustained-signal discipline (one sampled instant must never
        # actuate): a backlog streak scales up, an idle streak scales
        # down — both bounded to ±1 per tick inside [min, max].  Idle
        # is judged on incoming work + dispatch progress, NOT the
        # in-flight queue (a just-retired pool thread's sentinel — or a
        # wave completing right now — must not read as load)
        if qdepth > backlog_factor * disp:
            eng._backlog_streak += 1
            eng._idle_streak = 0
        elif tun["queue_incoming"] == 0 and not busy:
            eng._idle_streak += 1
            eng._backlog_streak = 0
        else:
            eng._backlog_streak = 0
            eng._idle_streak = 0
        applied, dir_ = None, None
        if eng._backlog_streak >= recover_ticks:
            eng._backlog_streak = 0
            dir_ = "up"
            # prefer another dispatcher; a batcher whose dispatcher
            # axis is structurally fixed (the mesh runs ONE program at
            # a time) or saturated grows completer depth instead
            if disp < disp_max:
                applied = b.set_tuning(dispatchers=disp + 1,
                                       completer_depth=depth)
            if (applied is None or applied["dispatchers"] == disp) \
                    and depth < depth_max:
                applied = b.set_tuning(completer_depth=depth + 1)
        elif eng._idle_streak >= recover_ticks:
            eng._idle_streak = 0
            dir_ = "down"
            if depth > depth_min:
                applied = b.set_tuning(completer_depth=depth - 1)
            if (applied is None or applied["completer_depth"] == depth) \
                    and disp > disp_min:
                applied = b.set_tuning(dispatchers=disp - 1,
                                       completer_depth=depth)
        # a transition is a REAL state change: a saturated/structurally
        # fixed knob (or a deferred pool retire) emits nothing
        if applied is None or (applied["dispatchers"],
                               applied["completer_depth"]) == (disp,
                                                               depth):
            return None
        new_disp = applied["dispatchers"]
        new_depth = applied["completer_depth"]
        eng.sb.config.set("index.device.dispatchers", new_disp)
        eng.sb.config.set("index.device.completerDepth", new_depth)
        return {
            "dir": dir_,
            "from": f"{disp}x{depth}",
            "to": f"{new_disp}x{new_depth}",
            "cause": (f"batcher queue depth {qdepth} vs {disp} "
                      f"dispatchers: {disp}x{depth} -> "
                      f"{new_disp}x{new_depth}"),
            "evidence": {"queue_depth": qdepth, "dispatchers": new_disp,
                         "completer_depth": new_depth},
        }

    def remote_peer_guard(eng: "ActuatorEngine"):
        fl = getattr(eng.sb, "fleet", None)
        sick = frozenset(fl.sick_peers(outlier_factor,
                                       min_mesh=outlier_min_mesh,
                                       min_peer=outlier_min_peer)) \
            if fl is not None else frozenset()
        old = eng._avoid_peers
        if sick == old:
            return None
        eng._avoid_peers = sick
        eng.sb.config.set("remotesearch.avoidPeers",
                          ",".join(sorted(sick)))
        added, healed = sorted(sick - old), sorted(old - sick)
        return {
            # any NEWLY avoided peer makes this a protective step, even
            # when another peer healed in the same tick (equal-size
            # membership churn must never read as a recovery)
            "dir": "down" if added else "up",
            "from": f"{len(old)} avoided", "to": f"{len(sick)} avoided",
            "cause": ("sick peers avoided: "
                      + (f"+{','.join(added)}" if added else "")
                      + (f" -{','.join(healed)}" if healed else "")),
            "evidence": {"avoided": sorted(sick), "added": added,
                         "healed": healed},
        }

    def device_rebuild(eng: "ActuatorEngine"):
        """Device-loss watchdog (ISSUE 10c): while the device is lost,
        ensure the store's background rebuild loop is actually alive
        (declaration starts it; a died thread restarts here), and emit
        one breadcrumb per loss/recovery EDGE — the incident that pages
        on the loss names the recovery machinery next to it."""
        ds = getattr(eng.sb.index, "devstore", None)
        lost = bool(getattr(ds, "device_lost", False)) \
            if ds is not None else False
        if lost and ds is not None:
            fn = getattr(ds, "start_rebuild", None)
            if fn is not None:
                fn()            # idempotent: no-op while alive
        was = eng._device_lost_seen
        if lost == was:
            return None
        eng._device_lost_seen = lost
        # operator-visible mirror (the live path reads the store flag)
        eng.sb.config.set("index.device.lost", 1 if lost else 0)
        recoveries = getattr(ds, "device_loss_recoveries", 0) \
            if ds is not None else 0
        losses = getattr(ds, "device_losses", 0) if ds is not None else 0
        return {
            "dir": "down" if lost else "up",
            "from": "serving" if lost else "lost",
            "to": "lost" if lost else "serving",
            "cause": ("device lost: host fallback + background rebuild"
                      if lost else
                      f"device serving resumed (recovery "
                      f"#{recoveries})"),
            "evidence": {"losses": losses, "recoveries": recoveries},
        }

    def merge_scheduler(eng: "ActuatorEngine"):
        """Write-path deferral (ISSUE 13c): while the serving SLO
        burns, the ingest scheduler parks compactions and tier
        promotions (the node's two heavy background moves); after
        `recoverTicks` consecutive healthy ticks it catches up —
        running the most aggressive deferred merge ask and resubmitting
        every parked promotion.  Same hysteresis discipline as the
        serving ladder: a flapping rule must not thrash the merge
        schedule."""
        sched = getattr(eng.sb, "ingest_scheduler", None)
        if sched is None:
            return None
        st = eng.rule_state("slo_serving_p95")
        if not sched.deferred:
            if st != "critical":
                return None
            sched.set_deferred(True)
            eng._merge_ok_streak = 0
            eng.sb.config.set("ingest.mergeDeferred", 1)
            return {
                "dir": "down", "from": "scheduling", "to": "deferred",
                "cause": ("slo_serving_p95 critical: compactions and "
                          "tier promotions deferred to protect "
                          "serving"),
                "evidence": {"rule_state": st,
                             **sched.counters()},
            }
        if st == "ok":
            eng._merge_ok_streak += 1
        else:
            eng._merge_ok_streak = 0
            return None
        if eng._merge_ok_streak < recover_ticks:
            return None
        eng._merge_ok_streak = 0
        sched.set_deferred(False)
        eng.sb.config.set("ingest.mergeDeferred", 0)
        ev = sched.catch_up()
        return {
            "dir": "up", "from": "deferred", "to": "scheduling",
            "cause": (f"serving recovered: catch-up ran "
                      f"(merge={ev['pending_merge_ran']}, "
                      f"{ev['promotions_resumed']} promotion(s) "
                      f"resumed)"),
            "evidence": {"rule_state": st, **ev},
        }

    return [
        Actuator("serving_ladder",
                 "degradation ladder driven by the slo_serving_p95 "
                 "burn-rate state (one rung down per sustained-burn "
                 f"tick, up after {recover_ticks} healthy ticks)",
                 ('yacy_health_rule{rule="slo_serving_p95"}',),
                 "serving.degradeLevel", serving_ladder),
        Actuator("batcher_autotune",
                 "dispatcher-count / completer-depth auto-tuning within "
                 f"[{disp_min},{disp_max}]x[{depth_min},{depth_max}] "
                 "from the batcher queue-depth gauges",
                 ('yacy_batcher_queue_depth{queue="incoming"}',
                  'yacy_batcher_queue_depth{queue="inflight"}',
                  'yacy_device_serving_total{counter="batch_dispatches"}'),
                 "index.device.dispatchers", batcher_autotune),
        Actuator("remote_peer_guard",
                 "skip remote-search peers whose gossiped digests report "
                 "critical health or an outlier serving p95",
                 ("yacy_fleet_peers",
                  "yacy_fleet_peer_reported_critical"),
                 "remotesearch.avoidPeers", remote_peer_guard),
        Actuator("device_rebuild",
                 "device-loss watchdog: keeps the background rebuild "
                 "alive while the device is lost; breadcrumbs every "
                 "loss/recovery edge (down=lost, up=serving resumed)",
                 ("yacy_device_lost",
                  'yacy_device_loss_total{event="recoveries"}'),
                 "index.device.lost", device_rebuild),
        Actuator("merge_scheduler",
                 "write-path deferral: parks RWI compactions and tier "
                 "promotions while the serving SLO burns, catches up "
                 f"after {recover_ticks} healthy ticks (down=deferred, "
                 "up=catch-up ran)",
                 ('yacy_health_rule{rule="slo_serving_p95"}',
                  "yacy_ingest_deferred"),
                 "ingest.mergeDeferred", merge_scheduler),
    ]


class ActuatorEngine:
    """Owns the actuator set and its transition bookkeeping.  Ticked by
    `HealthEngine.tick` right after rule evaluation (the sensing and
    the actuation share one cadence and one busy thread) — or directly
    by tests."""

    def __init__(self, sb):
        cfg = sb.config
        self.sb = sb
        self.enabled = cfg.get_bool("actuator.enabled", True)
        self.recover_ticks = max(1, cfg.get_int("actuator.recoverTicks", 3))
        self.tick_s = cfg.get_float("health.tickS", 5.0)
        self.actuators = build_actuators(cfg)
        # admission control: sustained rate = the existing host-access
        # limit (httpd.maxAccessPerHost.600s accesses per 600 s window),
        # burst = the SAME full windowed allowance — the bucket is the
        # old sliding-window policy restated, never tighter (a NAT'd
        # office or a busy peer that the old limit admitted must not
        # start seeing 429s); what changes is that denials now carry
        # the bucket's true refill time as Retry-After
        limit = max(1, cfg.get_int("httpd.maxAccessPerHost.600s", 6000))
        rate = limit / 600.0
        self.bucket = TokenBucketTable(
            capacity=cfg.get_float("actuator.admissionBurst",
                                   float(limit)),
            refill_per_s=rate)
        # ladder / autotune / peer-guard state (mutated by evaluators
        # under self._lock via tick)
        self.level = LEVEL_FULL
        self._ok_streak = 0
        self._backlog_streak = 0
        self._idle_streak = 0
        self._last_dispatches = 0
        self._avoid_peers: frozenset = frozenset()
        self._device_lost_seen = False    # device_rebuild edge memory
        self._merge_ok_streak = 0         # merge_scheduler hysteresis
        self.tick_count = 0
        self.shed_count = 0
        self.degraded_queries = [0] * N_LEVELS
        self._transitions: dict[tuple, int] = {}
        self.breadcrumbs: deque = deque(maxlen=256)
        # two locks on purpose: _tick_lock serializes whole decision
        # passes (evaluators block on batcher/config work — holding the
        # counter lock across them would stall every concurrent
        # note_query() on the serving path and every /metrics scrape);
        # _lock guards only the counter/breadcrumb mutations
        self._tick_lock = threading.Lock()
        self._lock = threading.Lock()
        # (mono ts, owner ladder level, owner retry_after_s) — the
        # rank-service worker's cached view of the owner's rung
        self._remote_state = (-1e9, 0, 0.0)

    # -- evaluation ----------------------------------------------------------

    def rule_state(self, rule_name: str) -> str:
        eng = getattr(self.sb, "health", None)
        if eng is None:
            return "ok"
        st = eng.states.get(rule_name)
        return st.state if st is not None else "ok"

    def _live_batcher(self):
        ds = getattr(self.sb.index, "devstore", None)
        return getattr(ds, "_batcher", None) if ds is not None else None

    def tick(self, now: float | None = None) -> int:
        """One decision pass over every actuator; returns the number of
        transitions taken this tick."""
        if not self.enabled:
            return 0
        now = time.time() if now is None else now
        taken = 0
        with self._tick_lock:
            self.tick_count += 1
            for act in self.actuators:
                try:
                    tr = act.evaluate(self)
                except Exception as e:   # a broken actuator must be VISIBLE
                    with self._lock:
                        self.breadcrumbs.append({
                            "ts": round(now, 3), "actuator": act.name,
                            "dir": "error",
                            "cause": f"actuator error: {e!r}",
                            "knob": act.knob})
                    continue
                if tr is None:
                    continue
                taken += 1
                key = (act.name, tr["dir"])
                with self._lock:
                    self._transitions[key] = \
                        self._transitions.get(key, 0) + 1
                    self.breadcrumbs.append({
                        "ts": round(now, 3), "actuator": act.name,
                        "dir": tr["dir"], "from": tr.get("from", ""),
                        "to": tr.get("to", ""), "knob": act.knob,
                        "cause": tr.get("cause", ""),
                        "evidence": tr.get("evidence", {})})
        return taken

    # -- serving-path surface ------------------------------------------------

    def effective_level(self) -> int:
        """The ladder rung the CURRENT request serves under: the local
        rung, or the owner process's rung when this node is a
        rank-service worker (the owner's ladder governs the shared
        arena; TTL-cached so the socket is asked at most 1/s).
        A disabled engine is INERT: level 0, regardless of whatever
        rung was in force when it was switched off."""
        if not self.enabled:
            return 0
        lvl = self.level
        ds = getattr(self.sb.index, "devstore", None)
        fn = getattr(ds, "serving_state", None)
        if fn is not None:
            now = time.monotonic()
            ts, remote, retry = self._remote_state
            if now - ts > 1.0:
                try:
                    st = fn()
                    if isinstance(st, dict):
                        remote = int(st.get("level", 0))
                        retry = float(st.get("retry_after_s", 0.0))
                    else:
                        remote, retry = 0, 0.0
                except Exception:
                    remote, retry = 0, 0.0
                self._remote_state = (now, remote, retry)
            lvl = max(lvl, remote)
        return lvl

    def serving_state(self) -> dict:
        """The owner-side answer to a worker's rank-service
        `serving_state` call.  A disabled owner reports full service —
        its frozen rung must not keep degrading the workers."""
        if not self.enabled:
            return {"level": 0, "retry_after_s": 0.0}
        return {"level": self.level,
                "retry_after_s": self.shed_retry_after_s()}

    def admit(self, client: str) -> tuple[bool, float]:
        """Admission-control gate for one request from `client`;
        (allowed, retry_after_s).  A disabled engine admits everything
        — the pre-actuator windowed host limit in httpd still stands."""
        if not self.enabled:
            return True, 0.0
        return self.bucket.acquire(client)

    def shed_retry_after_s(self) -> float:
        """Honest Retry-After while shedding: the hysteresis time the
        ladder needs to climb back even if the burn stops NOW (recovery
        ticks x tick cadence per rung above full), clamped sane.  A
        worker shedding at the OWNER's rung relays the owner's own
        recovery estimate (its local rung is typically 0)."""
        rungs = max(1, self.level)
        local = min(300.0, max(5.0,
                               rungs * self.recover_ticks * self.tick_s))
        _ts, remote_lvl, remote_retry = self._remote_state
        if remote_lvl > self.level and remote_retry > 0.0:
            return min(300.0, max(local, remote_retry))
        return local

    def note_query(self, level: int) -> None:
        """Per-level served-query accounting — the degrade_level
        histogram the headline artifact carries."""
        with self._lock:
            self.degraded_queries[min(max(level, 0), N_LEVELS - 1)] += 1

    def note_shed(self) -> None:
        with self._lock:
            self.shed_count += 1

    # -- observability -------------------------------------------------------

    def transition_counts(self) -> dict:
        """(actuator, dir) -> count, zero-filled for every registered
        actuator x {down, up} so the /metrics series always resolve."""
        out = {}
        with self._lock:
            for act in self.actuators:
                for d in ("down", "up"):
                    out[(act.name, d)] = self._transitions.get(
                        (act.name, d), 0)
            for key, v in self._transitions.items():
                out[key] = v
        return out

    def transitions_total(self) -> int:
        with self._lock:
            return sum(self._transitions.values())

    def recent_breadcrumbs(self, n: int = 64) -> list:
        with self._lock:
            return list(self.breadcrumbs)[-n:]

    def avoided_peers(self) -> frozenset:
        """Peers the remote scatter should skip; empty when the engine
        is disabled (a frozen avoid set must not keep skipping peers
        the guard can no longer heal)."""
        if not self.enabled:
            return frozenset()
        with self._lock:
            return self._avoid_peers

    # -- hygiene -------------------------------------------------------------

    def undefined_series(self) -> list:
        """Actuator series references that do NOT resolve against the
        live exposition — must be empty (the no-dead-actuators gate,
        mirroring `HealthEngine.undefined_series`)."""
        from .health import parse_exposition
        from ..server.servlets.monitoring import prometheus_text
        keys = set(parse_exposition(
            prometheus_text(self.sb, include_buckets=False)))
        missing = []
        for act in self.actuators:
            for s in act.series:
                if s not in keys:
                    missing.append(f"{act.name}: {s}")
        return missing
