"""Whitebox in-process forensics (ISSUE 20, ROADMAP 1c evidence side).

M89/M90 taught the fleet to name WHICH member straggled; this layer
explains what that member was *doing*.  Three instruments, one module:

1. **Sampling profiler** — a single daemon thread walking
   ``sys._current_frames()`` at an adaptive 25–100 Hz, folding each
   thread's Python stack into ``root;...;leaf`` strings aggregated per
   rotating 30 s window (6 retained, the histogram-window cadence).
   Every sample is tagged with the thread's ROLE resolved from the
   named-pool canon below, so "the completer pool is pegged in
   ``fetch_topk``" is one dict read, fleet-wide.

2. **Lock-wait observatory** — :class:`ObservedLock` /
   :class:`ObservedRLock` wrap the hot named locks (the
   ``HOT_LOCK_CENSUS`` below, policed by yacylint's ``raw-hot-lock``)
   and record acquisition wait + hold walls into the canonical
   ``lock.wait.{name}`` / ``lock.hold.{name}`` histogram families.  A
   hold exceeding the family's cached window p95 captures the HOLDER's
   stack — the postmortem reads who held the lock, not just that it was
   held.  The wrapper is also the single measurement point for the
   tail classifier's ``tail.lock_wait`` marker spans (it calls
   :func:`tailattr.note_lock_wait`), replacing the hand-rolled timing
   pairs that used to sit at individual ``with`` sites.

3. **Triggered deep capture** — tail verdicts (``lock_wait``,
   ``queue_wait``, ``collective_straggler``) and health ok→critical
   edges arm a bounded 100 Hz capture window; its top folded stacks +
   the lock table embed in flight-recorder incidents exactly like M89
   embeds the cause histogram.

The whole module follows the tracing discipline: with
:func:`set_enabled` off, the lock fast path is ONE extra attribute
read and the sampler parks — zero allocation, nothing recorded.
:func:`snapshot` is the wire form ``do_profsnap`` ships so a convicted
member's own profile can ride its conviction incident.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from . import histogram, tailattr

# -- thread-role canon --------------------------------------------------------

# the named-pool census: every long-lived pool/loop thread the runtime
# spawns maps to one role, so folded stacks and the fleet digest speak
# roles, not thread ids.  ZERO-FILLED in /metrics and indexed into the
# digest (like tailattr.CAUSES), so the tuple order is a wire contract:
# append only.
ROLES = ("dispatcher", "completer", "flusher", "member-runloop",
         "health-tick", "search-feeder", "sampler", "other")

# thread-name prefix -> role (first match wins)
_ROLE_PATTERNS = (
    ("devstore-batcher", "dispatcher"),
    ("meshstore-batcher", "dispatcher"),
    ("devstore-completer", "completer"),
    ("meshstore-completer", "completer"),
    ("devstore-former", "flusher"),
    ("devstore-rebuild", "flusher"),
    ("devstore-prewarm", "flusher"),
    ("meshstore-rebuild", "flusher"),
    ("mesh-runloop", "member-runloop"),
    ("15_health", "health-tick"),
    ("federated-search", "search-feeder"),
    ("prof-sampler", "sampler"),
)


def thread_role(name: str) -> str:
    for prefix, role in _ROLE_PATTERNS:
        if name.startswith(prefix):
            return role
    return "other"


# -- instrumented-lock census -------------------------------------------------

# "file::Class::attr" -> canonical lock name.  THE census yacylint's
# raw-hot-lock checker polices: each entry must exist in the named
# class and be constructed as ObservedLock/ObservedRLock (or carry a
# rawlock-ok exemption), and an entry matching nothing is a finding —
# the census cannot rot.
HOT_LOCK_CENSUS = {
    "yacy_search_server_tpu/index/devstore.py::DeviceSegmentStore::_lock":
        "devstore",
    "yacy_search_server_tpu/index/devstore.py::_QueryBatcher::_tune_lock":
        "devstore_tune",
    "yacy_search_server_tpu/index/rwi.py::RWIIndex::_lock": "rwi",
    "yacy_search_server_tpu/index/dense.py::DenseVectorStore::_fwd_lock":
        "dense_fwd",
    "yacy_search_server_tpu/parallel/distributed.py::MeshMember::_plock":
        "mesh_plock",
    "yacy_search_server_tpu/search/searchevent.py::SearchEventCache::_lock":
        "search_cache",
}

# the canonical lock names, in census order (zero-fill domain for the
# per-lock metrics; mirrored by the lock.wait/lock.hold families in
# histogram.CANONICAL — hygiene-tested)
LOCK_NAMES = tuple(sorted(set(HOT_LOCK_CENSUS.values())))

# a hold always captures the holder stack past this floor even before
# the first window rotation primes the p95 cache
HOLDER_MIN_MS = 1.0

# recording floor for the observatory's histogram families: below 10 us
# a wait/hold is the lock's own bookkeeping (an uncontended acquire is
# ~0.3 us), not contention evidence — skipping it keeps the enabled
# fast path at ~4 clock reads per acquire/release pair instead of two
# full Histogram.record calls, which is what holds --prof-overhead
# under its 2% budget on lock-heavy serving
RECORD_MIN_MS = 0.01

_enabled = True
_lock = threading.Lock()          # module state (windows, capture, registry)
_LOCKS: dict[str, "ObservedLock"] = {}

# counters (monotonic; /metrics + snapshot read them)
samples_total = 0
capture_windows_total = 0
holder_captures_total = 0


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def configure(cfg) -> None:
    """Read the prof.* knobs once at switchboard construction (the
    tailattr.configure model) and start the always-on sampler."""
    set_enabled(cfg.get_bool("prof.enabled", True))
    s = ensure_sampler()
    s.base_hz = cfg.get_float("prof.sampleHz", s.base_hz)
    s.burst_hz = cfg.get_float("prof.burstHz", s.burst_hz)


# -- folded stacks ------------------------------------------------------------

_MAX_DEPTH = 24          # leaf-most frames kept per stack
_MAX_STACKS = 256        # distinct folded stacks per window
_OWN_FILE = __file__


# code object -> "module:function" label; code objects are effectively
# permanent, so caching on them (which keeps them alive) trades a few
# KB for skipping basename+format work on every frame of every sample
_label_cache: dict = {}


def _fold(frame, leaf_line: bool = True) -> str:
    """``root;...;leaf`` with ``module:function`` frames (the leaf also
    carries its line — the straggling SITE, not just the function)."""
    parts: list[str] = []
    f = frame
    cache = _label_cache
    while f is not None and len(parts) < _MAX_DEPTH:
        code = f.f_code
        if code.co_filename != _OWN_FILE:
            lbl = cache.get(code)
            if lbl is None:
                mod = os.path.basename(code.co_filename)
                if mod.endswith(".py"):
                    mod = mod[:-3]
                lbl = f"{mod}:{code.co_name}"
                if len(cache) < 4096:
                    cache[code] = lbl
            if leaf_line and not parts:
                parts.append(f"{lbl}:{f.f_lineno}")
            else:
                parts.append(lbl)
        f = f.f_back
    return ";".join(reversed(parts))


class _Window:
    __slots__ = ("start", "samples", "stacks", "roles", "dropped")

    def __init__(self, start: float):
        self.start = start
        self.samples = 0
        # (role, folded) -> count
        self.stacks: dict[tuple[str, str], int] = {}
        self.roles: dict[str, int] = {}
        self.dropped = 0


class SamplingProfiler:
    """The always-on sampler: one daemon thread, adaptive cadence —
    ``base_hz`` (deployed: 25) in steady state, ``burst_hz`` (100)
    while a triggered capture window is armed."""

    WINDOW_S = 30.0
    RETAIN = 6
    CAPTURE_S = 2.0
    CAPTURE_COOLDOWN_S = 5.0

    def __init__(self, base_hz: float = 25.0, burst_hz: float = 100.0):
        self.base_hz = base_hz
        self.burst_hz = burst_hz
        self._stop = threading.Event()
        self._cur = _Window(time.monotonic())
        self._ring: deque[_Window] = deque(maxlen=self.RETAIN)
        self._capture: dict | None = None      # armed capture window
        self._last_capture_end = 0.0
        # thread NAME -> role (never ident-keyed: the OS recycles
        # idents, so a dead completer's ident can come back as a
        # batcher and a stale ident cache would mislabel it forever);
        # spares the prefix matching, while the ident -> Thread hop
        # rides threading's own _active registry instead of an
        # enumerate() list build per sample
        self._role_cache: dict[str, str] = {}
        # ident -> (id(leaf frame), lineno, folded): most threads are
        # PARKED (queue.get, selectors.select) and their leaf frame
        # object + line do not move between samples — reuse the folded
        # string instead of re-walking the whole stack; any execution
        # progress changes the lineno (or the frame object) and misses
        self._stack_memo: dict[int, tuple] = {}
        self.last_capture: dict | None = None  # finalized, wire-shaped
        self._thread = threading.Thread(
            target=self._run, name="prof-sampler", daemon=True)
        self._thread.start()

    # -- the sampling loop ---------------------------------------------------

    def _run(self) -> None:
        while True:
            cap = self._capture is not None
            hz = self.burst_hz if cap else self.base_hz
            if self._stop.wait(1.0 / max(1.0, hz)):
                return
            if _enabled:
                try:
                    self._sample()
                except Exception:   # lint: broad-except-ok(the sampler
                    # must survive any racing interpreter state — a dead
                    # sampler silently ends all whitebox evidence)
                    pass

    def _sample(self) -> None:
        global samples_total, capture_windows_total
        now = time.monotonic()
        me = threading.get_ident()
        frames = sys._current_frames()
        rc = self._role_cache
        memo = self._stack_memo
        active = getattr(threading, "_active", None)
        names = None if active is not None else \
            {t.ident: t.name for t in threading.enumerate()}
        with _lock:
            if now - self._cur.start >= self.WINDOW_S:
                self._ring.append(self._cur)
                self._cur = _Window(now)
            cap = self._capture
            if cap is not None and now >= cap["until"]:
                self._finalize_capture_locked(cap)
                cap = None
            for ident, frame in frames.items():
                if ident == me:
                    continue
                if active is not None:
                    th = active.get(ident)
                    name = th.name if th is not None else ""
                else:
                    name = names.get(ident, "")
                role = rc.get(name)
                if role is None:
                    role = thread_role(name)
                    if len(rc) < 512:
                        rc[name] = role
                fid = id(frame)
                lineno = frame.f_lineno
                ent = memo.get(ident)
                if ent is not None and ent[0] == fid \
                        and ent[1] == lineno:
                    folded = ent[2]
                else:
                    folded = _fold(frame)
                    if len(memo) < 1024:
                        memo[ident] = (fid, lineno, folded)
                    else:
                        memo.clear()
                if not folded:
                    continue
                w = self._cur
                w.samples += 1
                w.roles[role] = w.roles.get(role, 0) + 1
                key = (role, folded)
                if key in w.stacks or len(w.stacks) < _MAX_STACKS:
                    w.stacks[key] = w.stacks.get(key, 0) + 1
                else:
                    w.dropped += 1
                if cap is not None:
                    cap["samples"] += 1
                    cap["stacks"][key] = cap["stacks"].get(key, 0) + 1
                samples_total += 1
        del frames

    def _finalize_capture_locked(self, cap: dict) -> None:
        global capture_windows_total
        capture_windows_total += 1
        self.last_capture = {
            "reason": cap["reason"],
            "ts": cap["ts"],
            "samples": cap["samples"],
            "hz": self.burst_hz,
            "window_s": self.CAPTURE_S,
            "stacks": _top_stacks(cap["stacks"], 10),
        }
        self._capture = None
        self._last_capture_end = time.monotonic()

    # -- triggered deep capture ---------------------------------------------

    def trigger(self, reason: str) -> bool:
        """Arm one bounded high-rate capture window (no-op while one is
        armed or cooling down — a verdict storm must not pin the
        sampler at burst rate)."""
        if not _enabled:
            return False
        now = time.monotonic()
        with _lock:
            if self._capture is not None or \
                    now - self._last_capture_end < self.CAPTURE_COOLDOWN_S:
                return False
            self._capture = {"reason": reason, "ts": round(time.time(), 3),
                             "until": now + self.CAPTURE_S,
                             "samples": 0, "stacks": {}}
        return True

    # -- reading -------------------------------------------------------------

    def stacks(self, n: int = 12) -> list[dict]:
        """Top-N folded stacks aggregated over the retained windows."""
        agg: dict[tuple[str, str], int] = {}
        with _lock:
            for w in list(self._ring) + [self._cur]:
                for key, c in w.stacks.items():
                    agg[key] = agg.get(key, 0) + c
        return _top_stacks(agg, n)

    def role_samples(self) -> dict[str, int]:
        """samples per role over the retained windows, zero-filled over
        the ROLES canon (the /metrics + digest domain)."""
        out = {r: 0 for r in ROLES}
        with _lock:
            for w in list(self._ring) + [self._cur]:
                for role, c in w.roles.items():
                    out[role] = out.get(role, 0) + c
        return out

    def reset(self) -> None:
        with _lock:
            self._ring.clear()
            self._cur = _Window(time.monotonic())
            self._capture = None
            self._last_capture_end = 0.0
            self.last_capture = None

    def stop(self) -> None:
        self._stop.set()


def _top_stacks(agg: dict, n: int) -> list[dict]:
    top = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))[:max(0, n)]
    return [{"role": role, "stack": folded, "count": c}
            for (role, folded), c in top]


_SAMPLER: SamplingProfiler | None = None


def ensure_sampler() -> SamplingProfiler:
    """Start (once) and return the process-global sampler."""
    global _SAMPLER
    with _lock:
        if _SAMPLER is None:
            _SAMPLER = SamplingProfiler()
    return _SAMPLER


def sampler() -> SamplingProfiler | None:
    return _SAMPLER


def trigger(reason: str) -> bool:
    """Arm a deep-capture window on the running sampler (no-op when the
    sampler was never started or profiling is disabled — callers are
    hot paths and must stay zero-cost)."""
    s = _SAMPLER
    return s.trigger(reason) if s is not None and _enabled else False


# -- lock-wait observatory ----------------------------------------------------


class ObservedLock:
    """A named ``threading.Lock`` recording acquisition-wait and hold
    walls into the canonical ``lock.wait.{name}`` / ``lock.hold.{name}``
    families (non-trivial walls only — the ``RECORD_MIN_MS`` floor
    keeps uncontended bookkeeping out of the histograms AND off the hot
    path), emitting the tail classifier's lock-wait marker span on
    contended acquires (the ONE measurement point), and capturing the
    holder's stack when a hold exceeds the family's cached window p95.
    Disabled fast path: one module-flag read, straight delegation."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._lk = self._make_inner()
        self._wait_fam = "lock.wait." + name
        self._hold_fam = "lock.hold." + name
        self._t_hold = 0.0
        self.contended_total = 0
        self.holder_stacks: deque = deque(maxlen=4)
        with _lock:
            _LOCKS[name] = self

    def _make_inner(self):
        return threading.Lock()

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not _enabled:
            return self._lk.acquire(blocking, timeout)
        t0 = time.perf_counter()
        got = self._lk.acquire(blocking, timeout)
        wait_ms = (time.perf_counter() - t0) * 1000.0
        if wait_ms >= RECORD_MIN_MS:
            # unified verdict labels: the marker span the tail
            # classifier sums into lock_ms rides the same measurement
            tailattr.note_lock_wait(self.name, t0)
            histogram.observe(self._wait_fam, wait_ms)
            if wait_ms >= tailattr.LOCK_WAIT_MIN_MS:
                self.contended_total += 1
        if got:
            self._begin_hold()
        return got

    def release(self) -> None:
        if _enabled:
            self._end_hold()
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lk.locked()

    # -- hold accounting (called only by the holding thread) -----------------

    def _begin_hold(self) -> None:
        self._t_hold = time.perf_counter()

    def _end_hold(self) -> None:
        global holder_captures_total
        t0 = self._t_hold
        if not t0:
            return
        self._t_hold = 0.0
        hold_ms = (time.perf_counter() - t0) * 1000.0
        if hold_ms < RECORD_MIN_MS:
            return
        histogram.observe(self._hold_fam, hold_ms)
        h = histogram.get(self._hold_fam)
        gate = max(h.p95_cache if h is not None else 0.0, HOLDER_MIN_MS)
        if hold_ms >= gate:
            # over-threshold hold: capture the HOLDER's stack (we still
            # hold the lock — the release site is exactly the evidence)
            try:
                stack = _fold(sys._getframe())
            except Exception:   # lint: broad-except-ok(forensics must
                # never break the release path of a hot lock)
                return
            holder_captures_total += 1
            self.holder_stacks.append({
                "ts": round(time.time(), 3),
                "hold_ms": round(hold_ms, 3),
                "stack": stack})


class ObservedRLock(ObservedLock):
    """Reentrant variant: hold walls span the OUTERMOST acquire/release
    pair, and the ``_release_save``/``_acquire_restore``/``_is_owned``
    protocol is forwarded so ``threading.Condition(lock)`` keeps
    working (rwi wraps its store lock in a capacity Condition)."""

    _reentrant = True

    def __init__(self, name: str):
        super().__init__(name)
        self._depth = 0

    def _make_inner(self):
        return threading.RLock()

    def _begin_hold(self) -> None:
        # only the owning thread runs this (the lock is held)
        if self._depth == 0:
            self._t_hold = time.perf_counter()
        self._depth += 1

    def _end_hold(self) -> None:
        if self._depth > 0:
            self._depth -= 1
            if self._depth == 0:
                super()._end_hold()

    def locked(self) -> bool:
        # RLock has no .locked() before 3.12; owned-by-me is the useful
        # question for a reentrant lock anyway
        return self._lk._is_owned()

    # Condition(lock) protocol: wait() drops ALL recursion levels via
    # _release_save and reacquires them via _acquire_restore — hold
    # accounting must end/restart with them or a cond.wait would count
    # as a giant hold
    def _is_owned(self):
        return self._lk._is_owned()

    def _release_save(self):
        depth, self._depth = self._depth, 0
        t0, self._t_hold = self._t_hold, 0.0
        if _enabled and t0:
            hold_ms = (time.perf_counter() - t0) * 1000.0
            if hold_ms >= RECORD_MIN_MS:
                histogram.observe(self._hold_fam, hold_ms)
        return (self._lk._release_save(), depth)

    def _acquire_restore(self, state):
        inner, depth = state
        self._lk._acquire_restore(inner)
        self._depth = depth
        self._t_hold = time.perf_counter()


def observed_locks() -> list["ObservedLock"]:
    with _lock:
        return [v for _k, v in sorted(_LOCKS.items())]


def lock_table() -> list[dict]:
    """Per-lock wait/hold quantiles + contention + recent over-p95
    holder stacks — the table Performance_Prof_p and incident bodies
    render."""
    out = []
    for lk in observed_locks():
        row = {"name": lk.name, "contended_total": lk.contended_total,
               "holder_stacks": list(lk.holder_stacks)}
        for kind, fam in (("wait", lk._wait_fam), ("hold", lk._hold_fam)):
            h = histogram.get(fam)
            counts = h.windowed_counts() if h is not None else []
            n = sum(counts)
            row[kind] = {
                "count": n,
                "p50_ms": round(histogram.percentile_from_counts(
                    counts, 0.50), 3) if n else 0.0,
                "p95_ms": round(histogram.percentile_from_counts(
                    counts, 0.95), 3) if n else 0.0}
        out.append(row)
    return out


# -- wire form ----------------------------------------------------------------


def stats() -> dict:
    """The /metrics counters (zero-filled roles via role_samples)."""
    s = _SAMPLER
    return {
        "enabled": _enabled,
        "sampler_running": s is not None,
        "sampler_hz": (s.burst_hz if s is not None and
                       s._capture is not None else
                       s.base_hz if s is not None else 0.0),
        "samples_total": samples_total,
        "capture_windows_total": capture_windows_total,
        "holder_captures_total": holder_captures_total,
    }


def snapshot(top_n: int = 12) -> dict:
    """The whole whitebox picture in one wire-safe dict: what
    ``do_profsnap`` ships, what a conviction incident embeds, what
    Performance_Prof_p renders."""
    s = _SAMPLER
    return {
        "ts": round(time.time(), 3),
        "pid": os.getpid(),
        **stats(),
        "window_s": SamplingProfiler.WINDOW_S,
        "stacks": s.stacks(top_n) if s is not None else [],
        "roles": s.role_samples() if s is not None
        else {r: 0 for r in ROLES},
        "locks": lock_table(),
        "last_capture": s.last_capture if s is not None else None,
    }


def report(top_n: int = 8) -> dict:
    """The flight-recorder embed (ISSUE 20c): compact — top folded
    stacks + lock table + the last deep capture, no role zero-fill."""
    s = _SAMPLER
    return {
        "stacks": s.stacks(top_n) if s is not None else [],
        "locks": lock_table(),
        "last_capture": s.last_capture if s is not None else None,
    }


def top_role_index() -> int:
    """The fleet-digest compact form (the tailattr.CAUSES-index model):
    index into ROLES of the role with the most samples over the
    retained windows; 'other' when the sampler never ran."""
    s = _SAMPLER
    if s is None:
        return ROLES.index("other")
    roles = s.role_samples()
    top = max(ROLES, key=lambda r: (roles.get(r, 0), r != "other"))
    return ROLES.index(top)


def decode_role(i) -> str:
    """Tolerant decode of a digest's role index (version skew reads as
    'other' — which is zero-filled, so the series always resolves)."""
    try:
        i = int(i)
    except (TypeError, ValueError):
        i = -1
    return ROLES[i] if 0 <= i < len(ROLES) else "other"


def reset() -> None:
    """Test/bench isolation: drop windows, captures and counters (the
    sampler thread itself survives — it is process-global)."""
    global samples_total, capture_windows_total, holder_captures_total
    s = _SAMPLER
    if s is not None:
        s.reset()
    with _lock:
        samples_total = 0
        capture_windows_total = 0
        holder_captures_total = 0
        for lk in _LOCKS.values():
            lk.holder_stacks.clear()
            lk.contended_total = 0
