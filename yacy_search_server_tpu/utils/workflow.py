"""Host-side async pipeline scaffolding: WorkflowProcessor + BusyThread.

Capability equivalent of the reference's thread-pipeline substrate
(reference: source/net/yacy/kelondro/workflow/WorkflowProcessor.java and
AbstractBusyThread.java / InstantBusyThread.java): named bounded queues with
worker pools chained into a pipeline with backpressure, and periodic jobs
with idle/busy sleep plus memory preconditions. In the TPU build this is the
host pipeline that batches parse/condense work and feeds device step
functions; stages expose live metrics for the performance dashboard.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Generic, Optional, TypeVar

from .memory import MemoryControl

T = TypeVar("T")

_POISON = object()


@dataclass
class StageMetrics:
    name: str = ""
    enqueued: int = 0
    processed: int = 0
    errors: int = 0
    total_exec_ns: int = 0
    queue_size: int = 0
    workers: int = 0

    @property
    def avg_exec_ms(self) -> float:
        return (self.total_exec_ns / self.processed / 1e6) if self.processed else 0.0


class WorkflowProcessor(Generic[T]):
    """Named bounded queue + worker pool; `next_stage` receives results."""

    def __init__(self, name: str, task: Callable[[T], Optional[object]],
                 workers: int = 1, queue_size: int = 200,
                 next_stage: "WorkflowProcessor | None" = None):
        self.name = name
        self.task = task
        self.next_stage = next_stage
        self.queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self.metrics = StageMetrics(name=name, workers=workers)
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()
        self._running = True
        for i in range(workers):
            t = threading.Thread(target=self._loop, name=f"{name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def enqueue(self, item: T, block: bool = True, timeout: float | None = None) -> None:
        self.queue.put(item, block=block, timeout=timeout)
        with self._lock:
            self.metrics.enqueued += 1

    def _loop(self) -> None:
        while True:
            item = self.queue.get()
            if item is _POISON:
                self.queue.task_done()
                return
            t0 = time.monotonic_ns()
            try:
                result = self.task(item)
                if result is not None and self.next_stage is not None:
                    # bounded retry so a shut-down downstream stage cannot
                    # block this worker (and a later shutdown) forever
                    while self.next_stage._running:
                        try:
                            self.next_stage.enqueue(result, timeout=0.5)
                            break
                        except queue.Full:
                            continue
                with self._lock:
                    self.metrics.processed += 1
            except Exception:
                with self._lock:
                    self.metrics.errors += 1
            finally:
                with self._lock:
                    self.metrics.total_exec_ns += time.monotonic_ns() - t0
                self.queue.task_done()

    def queue_size(self) -> int:
        return self.queue.qsize()

    def join(self) -> None:
        self.queue.join()

    def shutdown(self, drain: bool = True) -> None:
        if not self._running:
            return
        if drain:
            self.queue.join()
        self._running = False
        for _ in self._threads:
            self.queue.put(_POISON)
        for t in self._threads:
            t.join(timeout=5)


class BusyThread:
    """Periodic job with idle/busy sleep and memory preconditions.

    `job` returns True when it did work (busy sleep next) and False when idle
    (idle sleep next) — the idle/busy pacing model of the reference's busy
    threads (AbstractBusyThread).
    """

    def __init__(self, name: str, job: Callable[[], bool],
                 idle_sleep_s: float = 10.0, busy_sleep_s: float = 1.0,
                 memory_floor_bytes: int = 0, start_delay_s: float = 0.0):
        self.name = name
        self.job = job
        self.idle_sleep_s = idle_sleep_s
        self.busy_sleep_s = busy_sleep_s
        self.memory_floor_bytes = memory_floor_bytes
        self.start_delay_s = start_delay_s
        self.busy_cycles = 0
        self.idle_cycles = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)

    def start(self) -> "BusyThread":
        self._thread.start()
        return self

    def _loop(self) -> None:
        if self.start_delay_s and self._stop.wait(self.start_delay_s):
            return
        while not self._stop.is_set():
            did_work = False
            if self.memory_floor_bytes and not MemoryControl.available() >= self.memory_floor_bytes:
                did_work = False
            else:
                try:
                    did_work = bool(self.job())
                except Exception:
                    self.errors += 1
            if did_work:
                self.busy_cycles += 1
                self._stop.wait(self.busy_sleep_s)
            else:
                self.idle_cycles += 1
                self._stop.wait(self.idle_sleep_s)

    def terminate(self, wait: bool = True) -> None:
        self._stop.set()
        if wait and self._thread.is_alive():
            self._thread.join(timeout=5)

    def is_alive(self) -> bool:
        return self._thread.is_alive()


class ThreadRegistry:
    """Named registry of busy threads (the switchboard's deployThread model)."""

    def __init__(self):
        self._threads: dict[str, BusyThread] = {}

    def deploy(self, thread: BusyThread) -> BusyThread:
        self._threads[thread.name] = thread
        return thread.start()

    def get(self, name: str) -> BusyThread | None:
        return self._threads.get(name)

    def names(self) -> list[str]:
        return sorted(self._threads)

    def terminate_all(self) -> None:
        for t in self._threads.values():
            t._stop.set()
        for t in self._threads.values():
            t.terminate()
