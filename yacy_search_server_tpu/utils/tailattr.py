"""Tail forensics — the p99 cause-attribution engine (ISSUE 15).

The observability spine built in rounds 6–10 *measures* a slow query
(windowed histograms, exemplars, burn-rate rules) but never *explains*
it: a cold-tier page-in, a deferred merge, a straggling mesh member all
land in the same anonymous fat p99 bucket, and a flight-recorder
incident names the symptom (``slo_serving_p95 critical``), not the
cause.  This module promotes the per-stage attribution discipline of
the trace spine into a CAUSAL layer: every over-threshold query gets
exactly one classified verdict.

Three parts:

- :class:`TailAttributor` — the classifier.  Hooked to root-span
  completion (``tracing.add_root_hook``), it reuses the cached-window-
  p95 gating from :mod:`utils.histogram` (the same gate that elects
  exemplars): a serving root at/above its family's window p95 (floored
  at ``MIN_MS``) is exemplar-worthy, so it gets classified.  The walk
  reads the trace's spans — cause markers emitted by the product paths
  (``tail.host_fallback`` / ``tail.cold_miss`` / ``tail.lock_wait`` /
  ``search.degraded``), the per-wave stamps the batchers attach to
  ``devstore.batch`` / ``mesh.batch`` spans, and the kernel span
  decomposition — and emits ONE dominant cause from :data:`CAUSES`
  into a zero-filled counter canon (``yacy_tail_cause_total{cause}``)
  plus a bounded verdict ring served by ``Performance_Tail_p``.
- :class:`MeshTimeline` — cross-process scatter assembly.  Mesh members
  return their step's span segment (queue wait, commit/collective-entry
  wait, local execution wall) inline on the next scatter reply (zero
  extra RPCs); the coordinator assembles a complete per-member timeline
  for every collective query, merges it into the trace ring (the
  ``assemble=1`` waterfall shows the whole mesh), finalizes verdicts
  that had to wait for segments (``collective_straggler`` NAMES the
  slowest member) and maintains the windowed straggler scoreboard (how
  often each member was the slowest leg, by how much).
- The wave log — a bounded ring of the batchers' dispatch-wave stamps
  (queue depth at enqueue, wave occupancy, compile-vs-reuse, tier/
  deferral state) so a query's slowness is attributable to *its wave*,
  not just its own spans.

Cause precedence under overlapping faults (ISSUE 19): two armed faults
can both plausibly explain one slow query — a cold-tier miss during a
mesh straggle, a compile charge on a degraded rung.  The classifier
emits exactly ONE cause, resolved by a fixed priority ladder
(:data:`PRECEDENCE`, pinned by the table-driven test in
tests/test_tailattr.py):

1. ``collective_straggler`` — the assembled mesh timeline NAMES the
   late member; cross-process evidence outranks every local marker.
2. ``host_fallback`` — the store KNOWS the device was lost; the query
   was answered on the host no matter what else was slow around it.
3. ``merge_deferral`` / ``tier_cold`` — the first cold-miss marker;
   one rung, split by the marker's ``deferred`` attr (the scheduler
   parked the promotion vs a plain cold miss).
4. ``compile`` — the wave stamp's compile-vs-reuse bit.
5. ``queue_wait`` — measured pre-issue wait >= 40% of the wall.
6. ``lock_wait`` — measured lock-acquisition wall >= 30% of the wall.
7. ``degraded_rung`` — served under a ladder rung with nothing above
   claiming the wall.
8. ``unattributed`` — no detector claimed it (the zero-unattributed
   game-day gate counts these).

Explicit markers outrank inferred dominance shares because the product
path that emitted the marker KNOWS why it slowed; dominance thresholds
are heuristics.

Straggler convictions (ISSUE 19 / ROADMAP 1c first slice, read-only):
:class:`ConvictionTracker` watches the windowed scoreboard; a member
that is the slowest leg of most steps for N consecutive windows is
CONVICTED — a flight-recorder breadcrumb + the zero-filled
``yacy_mesh_straggler_convictions_total{member}`` series.  Observation
only: steering/shedding on a conviction stays future work.

Jax-free by contract (imported by the wire layer and the chaos
children); zero-alloc when disabled — every product hook bails on one
module-flag read, the ``bench.py --tail-overhead`` A/B switch.
"""

from __future__ import annotations

import logging
import statistics
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from . import histogram, tracing

log = logging.getLogger("tailattr")

# the cause canon (zero-filled on /metrics so alert expressions and the
# fleet digest's top-1 field always resolve).  collective_straggler
# verdicts additionally NAME the member (verdict ring + scoreboard +
# yacy_tail_straggler_total{member}).
CAUSES = (
    "queue_wait",            # batcher wait dominated (pool saturated /
    #                          dispatcher wedged / backlog)
    "compile",               # the wave paid a first-use kernel compile
    "collective_straggler",  # one mesh member's step straggled the fleet
    "tier_cold",             # cold/warm tier miss: the query host-served
    #                          while its term's promotion was kicked
    "merge_deferral",        # the miss was parked by the merge/promotion
    #                          scheduler's serving-SLO deferral
    "lock_wait",             # measured lock-acquisition wall dominated
    "degraded_rung",         # the query served under a degradation rung
    "host_fallback",         # device lost / transfer failure: counted
    #                          host answer
    "unattributed",          # over threshold, no detector claimed it
)

# the classifier's tie-break ladder under overlapping faults, highest
# priority first (merge_deferral and tier_cold share one rung — the
# cold marker's `deferred` attr splits them).  classify() must consult
# detectors in exactly this order; the table-driven precedence test
# cross-references this tuple.
PRECEDENCE = (
    "collective_straggler",
    "host_fallback",
    "merge_deferral", "tier_cold",
    "compile",
    "queue_wait",
    "lock_wait",
    "degraded_rung",
    "unattributed",
)

# cause-marker span families the product paths emit (each creates a
# histogram family through the one span-record wiring point; the
# markers are 0 ms except lock_wait, which is a real measured wall)
MARKER_HOST_FALLBACK = "tail.host_fallback"
MARKER_COLD_MISS = "tail.cold_miss"
MARKER_LOCK_WAIT = "tail.lock_wait"
MARKER_DEGRADED = "search.degraded"        # emitted by SearchEvent (M83)

# histogram families the classifier consumes or gates on — the
# yacylint `tail-reach` checker requires any family a servlet wall
# observes to appear here (or carry a reasoned tail-ok lint
# exemption): a serving wall the classifier cannot reach is a p99
# bucket nothing can ever explain.
CLASSIFIER_FAMILIES = frozenset({
    "servlet.serving",
    "switchboard.search", "mesh.serve",
    "devstore.batch", "mesh.batch", "mesh.collective",
    "kernel.issue", "kernel.device", "kernel.fetch",
    MARKER_HOST_FALLBACK, MARKER_COLD_MISS, MARKER_LOCK_WAIT,
    MARKER_DEGRADED,
})

# roots eligible for classification: query-serving walls only — a
# pipeline/crawl root must never claim a tail verdict (the same
# discipline as histogram.BACKGROUND_PREFIXES)
SERVING_ROOT_PREFIXES = ("servlet.",)
SERVING_ROOT_NAMES = frozenset({"switchboard.search", "mesh.serve"})

# classification gate floor: the cached window p95 starts at 0 on a
# fresh family, and a microsecond root crossing a 0 gate would classify
# every healthy request
MIN_MS = 25.0
# a lock wait under this never emits a marker (uncontended acquires are
# the overwhelming hot path)
LOCK_WAIT_MIN_MS = 1.0
# dominance thresholds (fractions of the root wall).  Queue dominance
# judges the batcher-MEASURED pre-issue wait (submit -> wave issue),
# which excludes the query's own kernel work by construction — 40% of
# the wall spent purely waiting is a queue verdict.
QUEUE_DOMINANCE = 0.4
LOCK_DOMINANCE = 0.3
# a member is a straggler when its exec wall exceeds the median of the
# other members' by this factor AND carries a material share of the wall
STRAGGLER_FACTOR = 2.0
STRAGGLER_MIN_SHARE = 0.25

VERDICT_RING = 256
WAVE_RING = 128
SCOREBOARD_RING = 1024
MESH_RECORDS = 256

_enabled = True


def set_enabled(on: bool) -> None:
    """Global gate (the bench --tail-overhead A/B switch): disables
    classification AND the batchers' wave stamping in one flag."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def configure(cfg) -> None:
    """Read the tail.* knobs once at switchboard construction (the
    health-engine model for performance knobs)."""
    global MIN_MS
    set_enabled(cfg.get_bool("tail.enabled", True))
    MIN_MS = cfg.get_float("tail.minMs", MIN_MS)
    CONVICTIONS.configure(cfg)


@dataclass
class Verdict:
    """One classified over-threshold query."""

    ts: float
    trace_id: str
    root: str
    dur_ms: float
    cause: str
    member: str = ""
    evidence: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        out = {"ts": round(self.ts, 3), "trace_id": self.trace_id,
               "root": self.root, "dur_ms": round(self.dur_ms, 3),
               "cause": self.cause, "evidence": self.evidence}
        if self.member:
            out["member"] = self.member
        return out


def _p95_gate_ms(family: str) -> float:
    """The cached-window-p95 gate for a family (the histogram's
    exemplar election threshold), floored at MIN_MS."""
    h = histogram.get(family)
    return max(MIN_MS, h.p95_cache if h is not None else 0.0)


class TailAttributor:
    """The classifier + verdict ring + cause counters (process-global
    like the histogram registry it gates on)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.ring: deque = deque(maxlen=VERDICT_RING)
        self.cause_totals: dict[str, int] = {c: 0 for c in CAUSES}
        self.straggler_totals: dict[str, int] = {}
        self.classified_total = 0
        self.waves: deque = deque(maxlen=WAVE_RING)

    # -- recording surface ---------------------------------------------------

    def note_root(self, trace_id: str, name: str, dur_ms: float) -> None:
        """Root-span completion hook (tracing.add_root_hook): classify
        the trace when its wall clears the family's cached-window-p95
        exemplar gate."""
        if not _enabled:
            return
        if not (name in SERVING_ROOT_NAMES
                or name.startswith(SERVING_ROOT_PREFIXES)):
            return
        if dur_ms < _p95_gate_ms(name):
            return
        rec = tracing.get_trace(trace_id)
        if rec is None:
            return
        if name == "mesh.serve":
            # mesh verdicts need the members' span segments, which
            # arrive on the NEXT scatter reply: hand off to the
            # timeline, which finalizes (or defers) the verdict
            MESH.mark_pending(trace_id, dur_ms)
            return
        self.record(self.classify(rec, dur_ms))

    def note_wave(self, wave: dict) -> None:
        """One dispatch wave's stamp into the bounded wave log (the
        Performance_Tail_p wave table)."""
        if not _enabled:
            return
        with self._lock:
            self.waves.append(wave)

    # -- classification ------------------------------------------------------

    def classify(self, rec, dur_ms: float,
                 mesh_info: dict | None = None) -> Verdict:
        """Walk one trace's spans (+ the optional assembled mesh
        timeline) and emit exactly one dominant cause.  Detector order
        is a priority ladder: explicit markers (the product path KNOWS
        why it slowed) outrank inferred dominance shares."""
        host_fb = False
        cold = None                      # attrs of the first cold marker
        lock_ms = 0.0
        degraded_level = 0
        batch_ms = 0.0
        kernel_ms = 0.0
        queue_ms = 0.0
        wave_compile = False
        q_depth = 0
        wave_occ = 0.0
        for s in rec.spans:
            n = s.name
            if n == MARKER_HOST_FALLBACK:
                host_fb = True
            elif n == MARKER_COLD_MISS and cold is None:
                cold = s.attrs
            elif n == MARKER_LOCK_WAIT:
                lock_ms += s.dur_ms
            elif n == MARKER_DEGRADED:
                try:
                    degraded_level = max(degraded_level,
                                         int(s.attrs.get("level", 0)))
                except (TypeError, ValueError):
                    pass
            elif n in ("devstore.batch", "mesh.batch"):
                batch_ms += s.dur_ms
                a = s.attrs
                wave_compile = wave_compile or bool(a.get("wave_compile"))
                try:
                    q_depth = max(q_depth, int(a.get("wave_qdepth", 0)))
                    wave_occ = max(wave_occ,
                                   float(a.get("wave_occ", 0.0)))
                    # MEASURED pre-issue wait stamped by the batcher
                    # (submit -> wave issue) — never inferred by
                    # subtracting overlapping kernel spans
                    queue_ms += float(a.get("wave_queue_ms", 0.0))
                except (TypeError, ValueError):
                    pass
            elif n.startswith("kernel."):
                kernel_ms += s.dur_ms
        ev = {"batch_ms": round(batch_ms, 3),
              "kernel_ms": round(kernel_ms, 3),
              "queue_ms": round(queue_ms, 3),
              "lock_ms": round(lock_ms, 3),
              "wave_qdepth": q_depth, "wave_occ": round(wave_occ, 3),
              "gate_ms": round(_p95_gate_ms(rec.root_name), 3)}
        cause, member = "unattributed", ""
        if mesh_info is not None:
            ev.update(mesh_info.get("evidence", {}))
            if mesh_info.get("straggler"):
                cause, member = "collective_straggler", \
                    mesh_info["straggler"]
            elif mesh_info.get("host_fallback"):
                # the collective could not form (a member lost/down) or
                # declined the step: the answer came from the host
                # mirror.  Attributed to the member whose state forced
                # the fallback — a game-day loss window must never read
                # `unattributed` on the coordinator
                host_fb = True
                member = str(mesh_info.get("culprit", ""))
        if cause == "unattributed":
            if host_fb:
                cause = "host_fallback"
            elif cold is not None:
                cause = "merge_deferral" if cold.get("deferred") \
                    else "tier_cold"
                ev["tier"] = str(cold.get("tier", "?"))
            elif wave_compile:
                cause = "compile"
            elif queue_ms >= QUEUE_DOMINANCE * dur_ms:
                cause = "queue_wait"
            elif lock_ms >= LOCK_DOMINANCE * dur_ms:
                cause = "lock_wait"
            elif degraded_level > 0:
                cause = "degraded_rung"
                ev["level"] = degraded_level
        return Verdict(time.time(), rec.trace_id, rec.root_name,
                       dur_ms, cause, member, ev)

    def record(self, v: Verdict) -> None:
        with self._lock:
            self.ring.append(v)
            self.cause_totals[v.cause] = \
                self.cause_totals.get(v.cause, 0) + 1
            self.classified_total += 1
            if v.member:
                self.straggler_totals[v.member] = \
                    self.straggler_totals.get(v.member, 0) + 1
        # whitebox deep capture (ISSUE 20c): a contention/queueing/
        # straggler verdict arms one bounded high-rate profiler window
        # so the NEXT incident embeds what the process was doing while
        # the tail burned.  Lazy import (profiling imports this module);
        # trigger() is rate-limited and a no-op when disabled.
        if v.cause in ("lock_wait", "queue_wait", "collective_straggler"):
            from . import profiling
            profiling.trigger(f"tail.{v.cause}")

    # -- reading -------------------------------------------------------------

    def verdicts(self, n: int = 50) -> list:
        with self._lock:
            return list(self.ring)[-max(0, n):][::-1]

    def windowed_causes(self, horizon_s: float = 180.0) -> dict:
        """Cause -> count over the last `horizon_s` (zero-filled over
        the canon) — the histogram an incident embeds."""
        cut = time.time() - horizon_s
        out = {c: 0 for c in CAUSES}
        with self._lock:
            for v in self.ring:
                if v.ts >= cut:
                    out[v.cause] = out.get(v.cause, 0) + 1
        return out

    def top_cause(self, horizon_s: float = 180.0) -> str:
        """The windowed dominant cause (the fleet digest's top-1 field);
        'unattributed' when the window is empty — always a canon member,
        so the digest_series mapping resolves."""
        w = self.windowed_causes(horizon_s)
        best = max(w, key=lambda c: w[c])
        return best if w[best] > 0 else "unattributed"

    def counters(self) -> dict:
        with self._lock:
            return {"classified_total": self.classified_total,
                    "causes": dict(self.cause_totals),
                    "stragglers": dict(self.straggler_totals)}

    def wave_log(self, n: int = 50) -> list:
        with self._lock:
            return list(self.waves)[-max(0, n):][::-1]

    def reset(self) -> None:
        with self._lock:
            self.ring.clear()
            self.waves.clear()
            self.cause_totals = {c: 0 for c in CAUSES}
            self.straggler_totals = {}
            self.classified_total = 0


class MeshTimeline:
    """Coordinator-side assembly of the per-member step segments
    (ISSUE 15a).  One record per scattered step; segments arrive inline
    on later scatter replies and complete the record with zero extra
    RPCs.  Complete records feed the straggler scoreboard; records the
    classifier marked pending finalize their verdict the moment the
    last segment lands."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_seq: "OrderedDict[int, dict]" = OrderedDict()
        self._by_trace: dict[str, int] = {}
        self.segments_merged = 0
        # pending verdicts finalized from PARTIAL segments (a lull in
        # traffic means the missing members' segments have no later
        # scatter reply to ride) — counted, never silently dropped
        self.pending_partial = 0
        # (ts, slowest_member, margin_ms, exec_by_member) per COMPLETE
        # step — the scoreboard is windowed over this ring
        self._board: deque = deque(maxlen=SCOREBOARD_RING)
        # every member id this timeline has ever scattered to — the
        # zero-fill domain for the conviction series (a member with no
        # convictions must still expose a 0 sample)
        self.known: set[int] = set()

    def note_step(self, seq: int, trace_id: str, members,
                  mode: str, culprit: str = "") -> None:
        """Register a scattered step (called by the coordinator BEFORE
        its mesh.serve root closes, so a pending classification can
        find the record).  `culprit` names the member whose lost/down
        state forced a host-mode step — the later verdict attributes
        the host fallback to it."""
        if not _enabled:
            return
        with self._lock:
            self._by_seq[seq] = {
                "seq": int(seq), "trace_id": trace_id, "ts": time.time(),
                "members": set(int(m) for m in members), "mode": mode,
                "culprit": culprit,
                "segs": {}, "pending_ms": None, "dur_ms": 0.0}
            self.known.update(self._by_seq[seq]["members"])
            self._by_trace[trace_id] = int(seq)
            evicted = []
            while len(self._by_seq) > MESH_RECORDS:
                _, old = self._by_seq.popitem(last=False)
                self._by_trace.pop(old.get("trace_id", ""), None)
                evicted.append(old)
        # an evicted record still owing a verdict finalizes from its
        # PARTIAL segments (counted) — never a silent drop; the lull
        # case (no later scatter to carry the missing segments at all)
        # is flushed by flush_pending from the tail read surfaces
        for old in evicted:
            if old.get("pending_ms") is not None:
                self._finalize(old)

    def finish_step(self, seq: int, dur_ms: float) -> None:
        with self._lock:
            rec = self._by_seq.get(int(seq))
            if rec is not None:
                rec["dur_ms"] = float(dur_ms)

    def mark_pending(self, trace_id: str, dur_ms: float) -> None:
        """The classifier's deferred-verdict hand-off: finalize now if
        every segment already arrived, else when the last one lands."""
        with self._lock:
            seq = self._by_trace.get(trace_id)
            rec = self._by_seq.get(seq) if seq is not None else None
            if rec is None:
                return
            rec["pending_ms"] = float(dur_ms)
            complete = set(rec["segs"]) >= rec["members"]
        if complete:
            self._finalize(rec)

    def add_segment(self, seg: dict) -> None:
        """One member's step segment (q_ms / commit_ms / exec_ms /
        mode), shipped inline on a scatter reply or produced locally by
        the coordinator's own runloop."""
        if not _enabled or not isinstance(seg, dict):
            return
        try:
            seq = int(seg["seq"])
            member = int(seg["m"])
        except (KeyError, TypeError, ValueError):
            return
        with self._lock:
            rec = self._by_seq.get(seq)
            if rec is None or member in rec["segs"]:
                return
            rec["segs"][member] = {
                "m": member,
                "q_ms": float(seg.get("q_ms", 0.0)),
                "commit_ms": float(seg.get("commit_ms", 0.0)),
                "entry_ms": float(seg.get("entry_ms", 0.0)),
                "exec_ms": float(seg.get("exec_ms", 0.0)),
                "mode": str(seg.get("mode", "?")),
                "ts0": float(seg.get("ts0", 0.0))}
            self.segments_merged += 1
            complete = set(rec["segs"]) >= rec["members"]
            if complete:
                # straggler signal = LOCAL lateness (queue backlog +
                # pre-dispatch wall): in an SPMD collective every
                # member's exec wall inflates identically when one
                # member is late, so exec cannot name the culprit —
                # the member that ENTERED latest can (distributed.py
                # stamps entry_ms exactly for this)
                lates = {m: s["q_ms"] + s["entry_ms"]
                         for m, s in rec["segs"].items()}
                slowest = max(lates, key=lambda m: lates[m])
                others = [v for m, v in lates.items() if m != slowest]
                margin = lates[slowest] - (statistics.median(others)
                                           if others else 0.0)
                self._board.append((time.time(), slowest,
                                    max(0.0, margin),
                                    {m: s["exec_ms"]
                                     for m, s in rec["segs"].items()}))
        if complete:
            self._merge_into_trace(rec)
            if rec["pending_ms"] is not None:
                self._finalize(rec)

    def _merge_into_trace(self, rec: dict) -> None:
        """Inject the assembled per-member timeline into the trace ring
        so `Performance_Trace_p?trace=<id>&assemble=1` renders the mesh
        waterfall.  Rides merge_remote_spans: idempotent dedup, and the
        spans never re-feed the histograms (the members observed their
        own walls)."""
        tid = rec.get("trace_id", "")
        if not tracing.valid_trace_id(tid):
            return
        for m, s in sorted(rec["segs"].items()):
            ts0 = s["ts0"] or rec["ts"]
            spans = []
            t = ts0
            for short, name in (("q_ms", "mesh.member.queue_wait"),
                                ("commit_ms", "mesh.member.commit_wait"),
                                ("entry_ms", "mesh.member.local_entry"),
                                ("exec_ms", "mesh.member.exec")):
                spans.append({"sid": f"m{m}q{rec['seq']}{short[:-3]}",
                              "parent": "", "name": name, "ts": t,
                              "dur_ms": round(s[short], 3),
                              "attrs": {"member": f"mesh{m}",
                                        "mode": s["mode"]}})
                t += s[short] / 1000.0
            tracing.merge_remote_spans(tid, spans, source=f"mesh{m}")

    def _finalize(self, rec: dict) -> None:
        """Classify a pending over-threshold mesh step now that its
        timeline is complete: collective_straggler names the slowest
        member when its exec wall dominates.  Idempotent: the pending
        wall is claimed under the lock, so a mark_pending racing the
        last add_segment produces exactly one verdict."""
        with self._lock:
            claimed = rec["pending_ms"]
            rec["pending_ms"] = None
        if claimed is None:
            return
        partial = not (set(rec["segs"]) >= rec["members"])
        if partial:
            with self._lock:
                self.pending_partial += 1
        lates = {m: s["q_ms"] + s["entry_ms"]
                 for m, s in rec["segs"].items()}
        slowest = max(lates, key=lambda m: lates[m]) if lates else None
        straggler = ""
        dur = claimed
        if slowest is not None:
            others = [v for m, v in lates.items() if m != slowest]
            med = statistics.median(others) if others else 0.0
            if lates[slowest] >= max(STRAGGLER_FACTOR * med,
                                     STRAGGLER_MIN_SHARE * dur):
                straggler = f"mesh{slowest}"
        info = {"straggler": straggler,
                "evidence": {
                    "seq": rec["seq"], "mode": rec["mode"],
                    "late_ms_by_member": {f"mesh{m}": round(v, 3)
                                          for m, v in lates.items()},
                    "exec_ms_by_member": {
                        f"mesh{m}": round(s["exec_ms"], 3)
                        for m, s in rec["segs"].items()}}}
        # a step that answered from the host mirror (collective refused
        # or individually declined) is host_fallback, not unattributed:
        # no member ENTERED late, so the lateness test above can't fire,
        # but the coordinator knows exactly why the collective broke
        host_modes = sorted(m for m, s in rec["segs"].items()
                            if s["mode"] in ("host", "error"))
        if not straggler and (rec.get("mode") == "host" or host_modes):
            info["host_fallback"] = True
            info["culprit"] = rec.get("culprit", "")
            info["evidence"]["host_members"] = [
                f"mesh{m}" for m in host_modes]
        if partial:
            info["evidence"]["segments_partial"] = sorted(
                rec["members"] - set(rec["segs"]))
        trace = tracing.get_trace(rec.get("trace_id", ""))
        if trace is None:
            return
        ATTR.record(ATTR.classify(trace, dur, mesh_info=info))

    def flush_pending(self, max_age_s: float = 5.0) -> int:
        """Finalize pending verdicts whose segments never fully arrived
        — a straggled query at the END of a burst has no later scatter
        reply to carry the missing members' segments, and the contract
        is EVERY over-threshold query gets exactly one verdict.  After
        `max_age_s` the record finalizes from whatever segments exist
        (counted in `pending_partial`; with two or more the straggler
        can still be named).  Called from the tail read surfaces
        (MeshMember.info / Performance_Tail_p) — the operator asking is
        exactly when an owed verdict must stop waiting."""
        cut = time.time() - max_age_s
        with self._lock:
            due = [r for r in self._by_seq.values()
                   if r["pending_ms"] is not None and r["ts"] < cut]
        for rec in due:
            self._finalize(rec)
        return len(due)

    # -- reading -------------------------------------------------------------

    def scoreboard(self, horizon_s: float = 600.0) -> list:
        """Windowed per-member straggler rows: how often each member
        was the slowest leg of a complete step, and by how much."""
        cut = time.time() - horizon_s
        with self._lock:
            rows = [r for r in self._board if r[0] >= cut]
        steps = len(rows)
        members: dict[int, dict] = {}
        for _ts, slowest, margin, execs in rows:
            for m, v in execs.items():
                agg = members.setdefault(m, {
                    "member": f"mesh{m}", "steps": 0, "slowest": 0,
                    "margin_ms_sum": 0.0, "margin_ms_max": 0.0,
                    "exec_ms_sum": 0.0})
                agg["steps"] += 1
                agg["exec_ms_sum"] += v
            agg = members[slowest]
            agg["slowest"] += 1
            agg["margin_ms_sum"] += margin
            agg["margin_ms_max"] = max(agg["margin_ms_max"], margin)
        out = []
        for m in sorted(members):
            a = members[m]
            out.append({
                "member": a["member"], "steps": a["steps"],
                "slowest_count": a["slowest"],
                "slowest_frac": round(a["slowest"] / max(1, steps), 3),
                "mean_margin_ms": round(
                    a["margin_ms_sum"] / max(1, a["slowest"]), 3),
                "max_margin_ms": round(a["margin_ms_max"], 3),
                "mean_exec_ms": round(
                    a["exec_ms_sum"] / max(1, a["steps"]), 3)})
        return out

    def waterfall(self, seq: int | None = None) -> dict | None:
        """One assembled step's per-member timeline (newest complete
        record when `seq` is None) — the artifact/servlet rendering."""
        with self._lock:
            recs = list(self._by_seq.values())
        if seq is not None:
            recs = [r for r in recs if r["seq"] == int(seq)]
        for rec in reversed(recs):
            if rec["segs"] and set(rec["segs"]) >= rec["members"]:
                return {"seq": rec["seq"], "trace_id": rec["trace_id"],
                        "mode": rec["mode"],
                        "dur_ms": round(rec["dur_ms"], 3),
                        "members": [rec["segs"][m]
                                    for m in sorted(rec["segs"])]}
        return None

    def reset(self) -> None:
        with self._lock:
            self._by_seq.clear()
            self._by_trace.clear()
            self._board.clear()
            self.known.clear()
            self.segments_merged = 0


class ConvictionTracker:
    """ROADMAP 1c first slice, read-only (ISSUE 19): a member that is
    the slowest leg of most complete steps for N CONSECUTIVE scoreboard
    windows is *convicted* — one edge-triggered breadcrumb into the
    flight recorder plus the zero-filled
    ``yacy_mesh_straggler_convictions_total{member}`` series.  A single
    slow window (GC pause, one cold step) never convicts; a cleared
    fault breaks the streak and re-arms the edge.  Observation only:
    nothing reads a conviction to steer or shed — that is future work,
    and keeping this slice read-only is what makes it safe to land
    under the game-day soak."""

    def __init__(self):
        self._lock = threading.Lock()
        self.window_s = 30.0      # one evaluation window
        self.windows_needed = 2   # consecutive guilty windows to convict
        self.slowest_frac = 0.6   # guilty: slowest leg of >= this share
        self.min_steps = 3        # ... over at least this many steps
        self.min_margin_ms = 20.0  # ... by a material margin
        self._last_eval = 0.0
        self._streaks: dict[str, int] = {}
        self.totals: dict[str, int] = {}
        self.breadcrumbs: deque = deque(maxlen=64)
        # conviction hook (ISSUE 20d): the coordinator registers a
        # callable(crumb) here; observe() drives it OUTSIDE the tracker
        # lock on every conviction edge so the hook may do wire RPCs
        # (fetch the convicted member's profile snapshot) and attach
        # evidence to the crumb before it rides the flight recorder
        self._on_convicted = None

    def configure(self, cfg) -> None:
        self.window_s = cfg.get_float("tail.convictionWindowS",
                                      self.window_s)
        self.windows_needed = max(1, cfg.get_int(
            "tail.convictionWindows", self.windows_needed))
        self.slowest_frac = cfg.get_float("tail.convictionFrac",
                                          self.slowest_frac)
        self.min_steps = cfg.get_int("tail.convictionMinSteps",
                                     self.min_steps)
        self.min_margin_ms = cfg.get_float("tail.convictionMarginMs",
                                           self.min_margin_ms)

    def observe(self, now: float | None = None) -> list[dict]:
        """One health-tick hook: evaluate at most once per window
        (ticks are faster than windows), judge the last window's
        scoreboard, advance streaks, emit conviction breadcrumbs on the
        streak-reaches-N edge.  Members with no scoreboard rows (no
        mesh, or a member down) contribute nothing — absence of
        evidence never convicts, and it never ACQUITS either: a streak
        only resets when the member shows up in a window and is judged
        not guilty, so an idle window does not launder a straggler."""
        now = time.time() if now is None else now
        with self._lock:
            if now - self._last_eval < self.window_s:
                return []
            self._last_eval = now
        rows = MESH.scoreboard(self.window_s)
        guilty = {r["member"] for r in rows
                  if r["steps"] >= self.min_steps
                  and r["slowest_frac"] >= self.slowest_frac
                  and r["mean_margin_ms"] >= self.min_margin_ms}
        seen = {r["member"] for r in rows}
        convicted = []
        with self._lock:
            for member in seen | set(self._streaks):
                if member in guilty:
                    self._streaks[member] = \
                        self._streaks.get(member, 0) + 1
                    if self._streaks[member] == self.windows_needed:
                        self.totals[member] = \
                            self.totals.get(member, 0) + 1
                        row = next((r for r in rows
                                    if r["member"] == member), {})
                        crumb = {
                            "ts": round(now, 3), "member": member,
                            "windows": self.windows_needed,
                            "window_s": self.window_s,
                            "slowest_frac": row.get("slowest_frac"),
                            "mean_margin_ms": row.get("mean_margin_ms"),
                            "conviction_total": self.totals[member]}
                        self.breadcrumbs.append(crumb)
                        convicted.append(crumb)
                        log.warning("straggler convicted: %s", crumb)
                elif member in seen:
                    # present in the window but not guilty: the streak
                    # breaks.  Absent members keep theirs — no evidence
                    # either way.
                    self._streaks.pop(member, None)
        hook = self._on_convicted
        if hook is not None:
            for crumb in convicted:
                try:
                    hook(crumb)
                except Exception:   # lint: broad-except-ok(a failing
                    # evidence fetch must never break the conviction
                    # edge itself — the crumb still records)
                    log.exception("conviction hook failed: %s",
                                  crumb.get("member"))
        return convicted

    def set_conviction_hook(self, fn) -> None:
        """Register the coordinator's conviction-edge callback (ISSUE
        20d): called with each fresh conviction crumb, outside the
        tracker lock, before health embeds the crumb in an incident —
        the hook may mutate the crumb (attach the member's profile)."""
        self._on_convicted = fn

    def known_members(self) -> list[str]:
        """The zero-fill domain: every member the timeline ever
        scattered to, plus anyone already convicted."""
        with self._lock:
            out = set(self.totals)
        out.update(f"mesh{m}" for m in sorted(MESH.known))
        return sorted(out)

    def conviction_totals(self) -> dict:
        """member -> convictions, zero-filled over known members."""
        out = {m: 0 for m in self.known_members()}
        with self._lock:
            out.update(self.totals)
        return out

    def recent(self, n: int = 20) -> list[dict]:
        with self._lock:
            return list(self.breadcrumbs)[-max(0, n):]

    def reset(self) -> None:
        with self._lock:
            self._streaks.clear()
            self.totals.clear()
            self.breadcrumbs.clear()
            self._last_eval = 0.0
            self._on_convicted = None


# -- process-global singletons (the histogram-registry model) ----------------

ATTR = TailAttributor()
MESH = MeshTimeline()
CONVICTIONS = ConvictionTracker()


def stamp_wave(items: list, kernel: str, max_batch: int,
               first_use: bool, issue_ms: float,
               extra: dict | None = None) -> dict:
    """Build ONE dispatch wave's timeline stamp and attach it (plus the
    per-item MEASURED pre-issue wait, submit -> now) to every item —
    the shared builder both batchers call (devstore `_stamp_wave`,
    meshstore `_dispatch`), so wave evidence cannot diverge between
    them.  Items carry `t_submit`/`q_depth` from their submit path;
    `extra` is the store's tier/deferral snapshot."""
    now = time.perf_counter()
    waits = [(now - it["t_submit"]) * 1000.0 for it in items
             if "t_submit" in it]
    wave = {"ts": round(time.time(), 3), "kernel": kernel,
            "n": len(items),
            "occ": round(len(items) / max(1, max_batch), 3),
            "qdepth": max((it.get("q_depth", 0) for it in items),
                          default=0),
            "queue_wait_ms": round(max(waits, default=0.0), 3),
            "issue_ms": round(issue_ms, 3),
            "compile": bool(first_use),
            **(extra or {})}
    for it in items:
        it["wave"] = wave
        if "t_submit" in it:
            it["queue_wait_ms"] = (now - it["t_submit"]) * 1000.0
    ATTR.note_wave(wave)
    return wave


def note_lock_wait(name: str, t0: float) -> None:
    """Called as the FIRST statement inside a `with lock:` body with a
    perf_counter taken just before the `with`: the elapsed wall IS the
    acquisition wait.  Emits the lock-wait marker span (a real measured
    wall) when contended and a trace is active; the uncontended cost is
    one perf_counter read."""
    if not _enabled:
        return
    wait_ms = (time.perf_counter() - t0) * 1000.0
    if wait_ms >= LOCK_WAIT_MIN_MS and tracing.current() is not None:
        tracing.emit(MARKER_LOCK_WAIT, wait_ms, lock=name)


def _root_hook(trace_id: str, name: str, dur_ms: float) -> None:
    ATTR.note_root(trace_id, name, dur_ms)


tracing.add_root_hook(_root_hook)


# module-level conveniences (the surfaces health/monitoring import)

def windowed_causes(horizon_s: float = 180.0) -> dict:
    return ATTR.windowed_causes(horizon_s)


def cause_totals() -> dict:
    return dict(ATTR.counters()["causes"])


def straggler_totals() -> dict:
    return dict(ATTR.counters()["stragglers"])


def top_cause(horizon_s: float = 180.0) -> str:
    return ATTR.top_cause(horizon_s)


def verdicts(n: int = 50) -> list:
    return ATTR.verdicts(n)


def scoreboard(horizon_s: float = 600.0) -> list:
    return MESH.scoreboard(horizon_s)


def conviction_totals() -> dict:
    return CONVICTIONS.conviction_totals()


def conviction_breadcrumbs(n: int = 20) -> list:
    return CONVICTIONS.recent(n)


def reset() -> None:
    """Test/bench isolation: drop verdicts, waves and mesh records."""
    ATTR.reset()
    MESH.reset()
    CONVICTIONS.reset()
