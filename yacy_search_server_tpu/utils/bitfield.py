"""Appearance-flag bitfield for postings and query constraints.

The reference stores a 4-byte bitfield per posting (reference:
source/net/yacy/kelondro/util/Bitfield.java used by
kelondro/data/word/WordReferenceRow.java:49-69 column "z"). Here flags are a
plain int32 so whole postings blocks carry them as one dense device column
and constraint checks become vectorized AND-compare masks.

Flag positions (identical to the reference so wire/ranking semantics match):
- category flags (document/Tokenizer.java:51-56)
- appearance flags (kelondro/data/word/WordReferenceRow.java:104-110)
"""

from __future__ import annotations

# category flags (Tokenizer.java:51-56)
FLAG_CAT_INDEXOF = 0        # directory-listing page ("index of")
FLAG_CAT_HASLOCATION = 19   # page has location metadata
FLAG_CAT_HASIMAGE = 20      # page references image(s)
FLAG_CAT_HASAUDIO = 21      # page references audio
FLAG_CAT_HASVIDEO = 22      # page references video
FLAG_CAT_HASAPP = 23        # page references application files

# appearance flags (WordReferenceRow.java:104-110)
FLAG_APP_DC_DESCRIPTION = 24  # word appears in anchor/alt description text
FLAG_APP_DC_TITLE = 25        # word appears in title/headline
FLAG_APP_DC_CREATOR = 26      # word appears in author
FLAG_APP_DC_SUBJECT = 27      # word appears in header tags / descriptive part
FLAG_APP_DC_IDENTIFIER = 28   # word appears in url
FLAG_APP_EMPHASIZED = 29      # word is bold/italic/emphasized

ALL_FLAGS = 30


class Bitfield:
    """Mutable flag set backed by one int; `.value` is the dense column cell."""

    __slots__ = ("value",)

    def __init__(self, value: int = 0):
        self.value = int(value)

    def set(self, pos: int, on: bool = True) -> None:
        if on:
            self.value |= 1 << pos
        else:
            self.value &= ~(1 << pos)

    def get(self, pos: int) -> bool:
        return bool(self.value & (1 << pos))

    def or_(self, other: "Bitfield") -> None:
        self.value |= other.value

    def matches(self, constraint: int) -> bool:
        """True if every bit of `constraint` is set here (query constraints)."""
        return (self.value & constraint) == constraint

    def __int__(self) -> int:
        return self.value

    def __eq__(self, other) -> bool:
        return isinstance(other, Bitfield) and self.value == other.value

    def __repr__(self) -> str:
        return f"Bitfield({self.value:#x})"
