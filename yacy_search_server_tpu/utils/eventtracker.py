"""Per-stage event tracking — the tracing surface of the framework.

Capability equivalent of the reference's EventTracker (reference:
source/net/yacy/search/EventTracker.java:41): bounded in-memory time-series
per event class; every pipeline/search stage reports (label, count,
duration) and dashboards render them. Kept deliberately cheap: a deque per
class, no locks on the hot path beyond deque's own thread safety.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from enum import Enum

from . import histogram, tracing


class EClass(Enum):
    SEARCH = "search"
    WORDCACHE = "wordcache"
    MEMORY = "memory"
    PPM = "ppm"
    INDEX = "index"
    DHT = "dht"
    PEERPING = "peerping"
    CRAWL = "crawl"


@dataclass(frozen=True)
class Event:
    ts: float
    label: str
    count: int
    duration_ms: float


_MAX_EVENTS = 4096
_series: dict[EClass, deque] = {c: deque(maxlen=_MAX_EVENTS) for c in EClass}
# cumulative (events, items, duration_ms) per (class, label): the
# monotonic counters /metrics exposes — the bounded deques above are a
# WINDOW, which a Prometheus counter must never be scraped from.
# Locked: += on a shared cell is a read-modify-write, and a Prometheus
# COUNTER that loses increments under thread interleaving is broken by
# contract (update() runs per stage, not per row — the lock is cold)
import threading as _threading

_totals: dict[tuple[EClass, str], list] = {}
_totals_lock = _threading.Lock()


def update(eclass: EClass, label: str, count: int = 0, duration_ms: float = 0.0) -> None:
    _series[eclass].append(Event(time.time(), label, count, duration_ms))
    with _totals_lock:
        tot = _totals.get((eclass, label))
        if tot is None:
            _totals[(eclass, label)] = [1, count, duration_ms]
        else:
            tot[0] += 1
            tot[1] += count
            tot[2] += duration_ms


def events(eclass: EClass) -> list[Event]:
    return list(_series[eclass])


def totals() -> dict[tuple[EClass, str], tuple[int, int, float]]:
    """Cumulative (events, items, duration_ms) per series since process
    start (the /metrics exposition surface)."""
    with _totals_lock:
        return {k: (v[0], v[1], v[2]) for k, v in _totals.items()}


def clear(eclass: EClass | None = None) -> None:
    if eclass is None:
        for d in _series.values():
            d.clear()
    else:
        _series[eclass].clear()


class StageTimer:
    """Context manager reporting one stage's wall time on exit.

    Doubles as the eventtracker→tracing bridge: when a trace is active
    on the calling context, the stage is ALSO recorded as a span named
    ``<class>.<label>`` — every existing StageTimer site (search
    stages, pipeline stages, crawl stages) joins the trace waterfall
    without a second timing call. Outside a trace the span handle is
    the shared no-op object (zero alloc).

    Histogram bridge (ISSUE 4): a traced stage reaches the windowed
    histograms through the span record (with its trace-id exemplar); an
    UNTRACED stage records here directly — so the per-stage p50/p95 on
    `/metrics` covers the whole workload, not just the traced slice."""

    def __init__(self, eclass: EClass, label: str, count: int = 0):
        self.eclass, self.label, self.count = eclass, label, count

    def __enter__(self):
        self._span = tracing.span(
            f"{self.eclass.value}.{self.label.lower()}")
        self._span.__enter__()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        ms = (time.monotonic() - self._t0) * 1000.0
        update(self.eclass, self.label, self.count, ms)
        self._span.__exit__(*exc)
        if self._span is tracing._NOOP:
            histogram.observe(
                f"{self.eclass.value}.{self.label.lower()}", ms)
        return False
