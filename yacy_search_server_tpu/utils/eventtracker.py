"""Per-stage event tracking — the tracing surface of the framework.

Capability equivalent of the reference's EventTracker (reference:
source/net/yacy/search/EventTracker.java:41): bounded in-memory time-series
per event class; every pipeline/search stage reports (label, count,
duration) and dashboards render them. Kept deliberately cheap: a deque per
class, no locks on the hot path beyond deque's own thread safety.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from enum import Enum


class EClass(Enum):
    SEARCH = "search"
    WORDCACHE = "wordcache"
    MEMORY = "memory"
    PPM = "ppm"
    INDEX = "index"
    DHT = "dht"
    PEERPING = "peerping"
    CRAWL = "crawl"


@dataclass(frozen=True)
class Event:
    ts: float
    label: str
    count: int
    duration_ms: float


_MAX_EVENTS = 4096
_series: dict[EClass, deque] = {c: deque(maxlen=_MAX_EVENTS) for c in EClass}


def update(eclass: EClass, label: str, count: int = 0, duration_ms: float = 0.0) -> None:
    _series[eclass].append(Event(time.time(), label, count, duration_ms))


def events(eclass: EClass) -> list[Event]:
    return list(_series[eclass])


def clear(eclass: EClass | None = None) -> None:
    if eclass is None:
        for d in _series.values():
            d.clear()
    else:
        _series[eclass].clear()


class StageTimer:
    """Context manager reporting one stage's wall time on exit."""

    def __init__(self, eclass: EClass, label: str, count: int = 0):
        self.eclass, self.label, self.count = eclass, label, count

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        update(self.eclass, self.label, self.count,
               (time.monotonic() - self._t0) * 1000.0)
        return False
