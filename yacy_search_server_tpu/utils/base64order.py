"""Base64 ordering, encoding and cardinal projection.

TPU-native re-design of the reference's byte-order substrate
(reference: source/net/yacy/cora/order/Base64Order.java). The DHT ring
position of every term and document is derived from the *cardinal* of its
base64 hash (reference: source/net/yacy/cora/federate/yacy/Distribution.java:74-78),
so this module is kept bit-compatible with the reference:

- alphabet "enhanced" (filename-safe): A-Za-z0-9-_  (Base64Order.java:38)
- alphabet "standard" (rfc1521):       A-Za-z0-9+/  (Base64Order.java:37)
- cardinal(key): first 10 base64 chars -> 60 bits, shifted left 3, OR 7,
  producing a value in [0, 2^63) (Base64Order.java:307-325 `cardinalI`).

Unlike the reference (per-byte Java loops), bulk variants here are
vectorized with numpy so millions of hashes can be projected onto the DHT
ring in one shot — that array then feeds device-side partition routing.
"""

from __future__ import annotations

import numpy as np

ALPHA_STANDARD = b"ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/"
ALPHA_ENHANCED = b"ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_"

LONG_MAX = (1 << 63) - 1


def _inverse(alpha: bytes) -> np.ndarray:
    # 256 entries so any byte value indexes in-range and fails the v<0 check
    inv = np.full(256, -1, dtype=np.int16)
    for i, c in enumerate(alpha):
        inv[c] = i
    return inv


class Base64Order:
    """Order, codec and cardinal projection over a base64 alphabet."""

    def __init__(self, rfc1521compliant: bool = False):
        self.rfc1521compliant = rfc1521compliant
        self.alpha = ALPHA_STANDARD if rfc1521compliant else ALPHA_ENHANCED
        self.ahpla = _inverse(self.alpha)

    # -- codec ---------------------------------------------------------------

    def encode_long(self, value: int, length: int) -> bytes:
        """Encode an integer into `length` base64 chars, most significant first."""
        out = bytearray(length)
        for i in range(length - 1, -1, -1):
            out[i] = self.alpha[value & 0x3F]
            value >>= 6
        return bytes(out)

    def decode_long(self, key: bytes | str) -> int:
        if isinstance(key, str):
            key = key.encode("ascii")
        c = 0
        for b in key:
            v = int(self.ahpla[b])
            if v < 0:
                raise ValueError(f"not base64: {key!r}")
            c = (c << 6) | v
        return c

    def encode(self, data: bytes) -> bytes:
        """Encode bytes to base64. Non-rfc variant emits no '=' padding."""
        out = bytearray()
        n = len(data)
        i = 0
        while i + 3 <= n:
            x = (data[i] << 16) | (data[i + 1] << 8) | data[i + 2]
            out += self.encode_long(x, 4)
            i += 3
        rem = n - i
        if rem == 2:
            x = (data[i] << 16) | (data[i + 1] << 8)
            out += self.encode_long(x, 4)[:3]
            if self.rfc1521compliant:
                out += b"="
        elif rem == 1:
            x = data[i] << 16
            out += self.encode_long(x, 4)[:2]
            if self.rfc1521compliant:
                out += b"=="
        return bytes(out)

    def encode_substring(self, data: bytes, length: int) -> bytes:
        """First `length` chars of the base64 encoding (hash truncation)."""
        return self.encode(data)[:length]

    def decode(self, key: bytes | str) -> bytes:
        if isinstance(key, str):
            key = key.encode("ascii")
        key = key.rstrip(b"=")
        out = bytearray()
        i = 0
        n = len(key)
        while i + 4 <= n:
            x = self.decode_long(key[i : i + 4])
            out += bytes(((x >> 16) & 0xFF, (x >> 8) & 0xFF, x & 0xFF))
            i += 4
        rem = n - i
        if rem == 3:
            x = self.decode_long(key[i : i + 3]) << 6
            out += bytes(((x >> 16) & 0xFF, (x >> 8) & 0xFF))
        elif rem == 2:
            x = self.decode_long(key[i : i + 2]) << 12
            out += bytes(((x >> 16) & 0xFF,))
        elif rem == 1:
            raise ValueError(f"truncated base64 input (length % 4 == 1): {key!r}")
        return bytes(out)

    def decode_byte(self, b: int) -> int:
        v = int(self.ahpla[b])
        if v < 0:
            raise ValueError(f"not base64 char: {b}")
        return v

    # -- ordering ------------------------------------------------------------

    def compare(self, a: bytes, b: bytes) -> int:
        for x, y in zip(a, b):
            vx, vy = int(self.ahpla[x]), int(self.ahpla[y])
            if vx != vy:
                return -1 if vx < vy else 1
        return (len(a) > len(b)) - (len(a) < len(b))

    def wellformed(self, a: bytes) -> bool:
        return all(b < 128 and self.ahpla[b] >= 0 for b in a)

    # -- cardinal projection -------------------------------------------------

    def cardinal(self, key: bytes | str) -> int:
        """Project a base64 key onto [0, 2^63): 10 chars = 60 bits, <<3 | 7."""
        if isinstance(key, str):
            key = key.encode("ascii")
        c = 0
        lim = min(10, len(key))
        for i in range(lim):
            v = int(self.ahpla[key[i]])
            if v < 0:
                raise ValueError(f"not base64: {key!r}")
            c = (c << 6) | v
        c <<= 6 * (10 - lim)
        return (c << 3) | 7

    def uncardinal(self, c: int) -> bytes:
        """Inverse of cardinal (up to the 3 dropped low bits): 10 chars."""
        c >>= 3
        return self.encode_long(c, 10)

    def cardinal_array(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized cardinal over an array of fixed-width base64 keys.

        keys: uint8 array [n, width] of ascii base64 chars (width >= 1).
        Returns int64 [n] of ring positions. This is the bulk DHT-projection
        primitive that replaces the reference's per-key Java calls.
        """
        assert keys.ndim == 2
        vals = self.ahpla[keys.astype(np.int64)].astype(np.int64)
        if np.any(vals < 0):
            raise ValueError("non-base64 byte in key array")
        width = min(10, keys.shape[1])
        c = np.zeros(len(keys), dtype=np.int64)
        for i in range(width):
            c = (c << 6) | vals[:, i]
        c = c << (6 * (10 - width))
        return (c << 3) | 7


standard_coder = Base64Order(rfc1521compliant=True)
enhanced_coder = Base64Order(rfc1521compliant=False)


def hashes_to_uint8(hashes: list[bytes], width: int = 12) -> np.ndarray:
    """Pack a list of fixed-width hash byte-strings into a uint8 [n, width] array."""
    arr = np.frombuffer(b"".join(hashes), dtype=np.uint8)
    return arr.reshape(len(hashes), width)
