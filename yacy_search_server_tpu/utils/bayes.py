"""Naive-bayes text classifier.

Capability equivalent of the reference's bayes package (reference:
source/net/yacy/cora/bayes/Classifier.java + BayesClassifier.java, ~715
LoC — feature=word counting per category with Laplace smoothing, used by
document/ProbabilisticClassifier to auto-tag documents from trained
context vocabularies). Scoring is vectorized: the learned log-likelihood
matrix is a numpy [category, vocab] array applied to a count vector.
"""

from __future__ import annotations

import math
import re
from collections import Counter

import numpy as np

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)


def _tokens(text: str) -> list[str]:
    return [t.lower() for t in _TOKEN_RE.findall(text) if len(t) > 2]


class BayesClassifier:
    def __init__(self):
        self._counts: dict[str, Counter] = {}
        self._docs: dict[str, int] = {}
        self._vocab: dict[str, int] | None = None
        self._loglik: np.ndarray | None = None
        self._logprior: np.ndarray | None = None
        self._cats: list[str] = []

    # -- training -------------------------------------------------------------

    def learn(self, category: str, text: str) -> None:
        self._counts.setdefault(category, Counter()).update(_tokens(text))
        self._docs[category] = self._docs.get(category, 0) + 1
        self._vocab = None      # invalidate the compiled matrices

    def categories(self) -> list[str]:
        return sorted(self._counts)

    def _compile(self) -> None:
        self._cats = self.categories()
        vocab_set: set[str] = set()
        for c in self._cats:
            vocab_set.update(self._counts[c])
        self._vocab = {w: i for i, w in enumerate(sorted(vocab_set))}
        v = len(self._vocab)
        mat = np.zeros((len(self._cats), v), dtype=np.float64)
        for ci, c in enumerate(self._cats):
            for w, n in self._counts[c].items():
                mat[ci, self._vocab[w]] = n
        totals = mat.sum(axis=1, keepdims=True)
        # Laplace smoothing
        self._loglik = np.log((mat + 1.0) / (totals + v))
        ndocs = sum(self._docs.values())
        self._logprior = np.array(
            [math.log(self._docs[c] / ndocs) for c in self._cats])

    # -- classification -------------------------------------------------------

    def scores(self, text: str) -> dict[str, float]:
        if not self._counts:
            return {}
        if self._vocab is None:
            self._compile()
        vec = np.zeros(len(self._vocab), dtype=np.float64)
        oov = 0
        for t in _tokens(text):
            i = self._vocab.get(t)
            if i is None:
                oov += 1
            else:
                vec[i] += 1
        logp = self._logprior + self._loglik @ vec
        return dict(zip(self._cats, logp.tolist()))

    def classify(self, text: str, min_margin: float = 0.0) -> str | None:
        """Best category, or None when the margin over the runner-up is
        below `min_margin` (unsure)."""
        s = self.scores(text)
        if not s:
            return None
        ranked = sorted(s.items(), key=lambda kv: -kv[1])
        if len(ranked) > 1 and ranked[0][1] - ranked[1][1] < min_margin:
            return None
        return ranked[0][0]
