"""ctypes bindings to the native C++ data-plane kernels (native/yacytpu.cpp).

The compute path of this framework is JAX/XLA/Pallas on device; this module
is the native *runtime* around it — the host-side feeding kernels that the
reference implements as concurrent Java (per-word MD5+base64 hashing,
Word.java:113-130; posting-row sorts and hash-probe joins,
ReferenceContainer.java:397-489). Loading is best-effort:

- `YACYTPU_NATIVE=0` disables the native path entirely;
- if `native/libyacytpu.so` is missing, it is compiled once with g++;
- on any failure `LIB` stays None and callers fall back to numpy — the
  native path and the fallback are interchangeable call-for-call (parity
  is enforced by tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libyacytpu.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "yacytpu.cpp")

_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)

_load_lock = threading.Lock()
_loaded = False
LIB: ctypes.CDLL | None = None


# below these sizes the ctypes call overhead beats the kernel win; wrappers
# return None and callers stay on their numpy/Python path
MIN_BATCH = 64
MIN_HASH_BATCH = 16


def _build() -> bool:
    # compile to a temp path + atomic rename: another process scanning the
    # directory must never dlopen a half-written ELF
    tmp = f"{_SO_PATH}.tmp.{os.getpid()}"
    try:
        res = subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
             "-o", tmp, _SRC_PATH],
            capture_output=True, timeout=120)
        if res.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, _SO_PATH)
        return True
    except (OSError, subprocess.SubprocessError):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _bind(lib: ctypes.CDLL) -> None:
    lib.ytn_abi_version.restype = ctypes.c_int32
    lib.ytn_word_hash_batch.argtypes = [_u8p, _i64p, ctypes.c_int64, _u8p]
    lib.ytn_word_hash_batch.restype = None
    lib.ytn_sort_dedupe.argtypes = [_i32p, ctypes.c_int64, _i64p]
    lib.ytn_sort_dedupe.restype = ctypes.c_int64
    lib.ytn_intersect.argtypes = [_i32p, ctypes.c_int64, _i32p, ctypes.c_int64,
                                  _i64p, _i64p]
    lib.ytn_intersect.restype = ctypes.c_int64
    lib.ytn_remove_docids.argtypes = [_i32p, ctypes.c_int64, _i32p,
                                      ctypes.c_int64, _u8p]
    lib.ytn_remove_docids.restype = None


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None on any failure."""
    global _loaded, LIB
    if _loaded:
        return LIB
    with _load_lock:
        if _loaded:
            return LIB
        if os.environ.get("YACYTPU_NATIVE", "1") == "0":
            _loaded = True
            return None
        try:
            if not os.path.exists(_SO_PATH) or (
                    os.path.exists(_SRC_PATH)
                    and os.path.getmtime(_SRC_PATH) > os.path.getmtime(_SO_PATH)):
                if not os.path.exists(_SRC_PATH) or not _build():
                    _loaded = True
                    return None
            lib = ctypes.CDLL(_SO_PATH)
            _bind(lib)
            if lib.ytn_abi_version() != 1:
                raise OSError("abi mismatch")
            LIB = lib
        except (OSError, AttributeError):  # AttributeError: missing symbol
            LIB = None
        _loaded = True
        return LIB


def available() -> bool:
    return load() is not None


def _as_i32(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


# -- wrappers (callers must check available() or handle None LIB) ------------

def word_hash_batch(words: list[str]) -> list[bytes] | None:
    """12-char word hashes for a batch of (not yet lowercased) tokens.

    Bit-compatible with utils/hashes.word2hash. Returns None when the
    native library is unavailable or the batch is too small to pay the
    call overhead (caller falls back to the Python path).
    """
    if len(words) < MIN_HASH_BATCH:
        return None
    lib = load()
    if lib is None:
        return None
    enc = [w.lower().encode("utf-8") for w in words]
    n = len(enc)
    if n == 0:
        return []
    blob = b"".join(enc)
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(e) for e in enc], out=offs[1:])
    buf = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(1, np.uint8)
    buf = np.ascontiguousarray(buf)
    out = np.empty(n * 12, dtype=np.uint8)
    lib.ytn_word_hash_batch(
        buf.ctypes.data_as(_u8p), offs.ctypes.data_as(_i64p),
        ctypes.c_int64(n), out.ctypes.data_as(_u8p))
    raw = out.tobytes()
    return [raw[12 * i: 12 * i + 12] for i in range(n)]


def sort_dedupe_order(docids: np.ndarray,
                      min_batch: int = MIN_BATCH) -> np.ndarray | None:
    """Original-row indices of surviving postings in ascending-docid order
    (last-wins dedupe); None when native is unavailable or input is small."""
    if len(docids) < min_batch:
        return None
    lib = load()
    if lib is None:
        return None
    d = _as_i32(docids)
    order = np.empty(len(d), dtype=np.int64)
    m = lib.ytn_sort_dedupe(d.ctypes.data_as(_i32p), ctypes.c_int64(len(d)),
                            order.ctypes.data_as(_i64p))
    return order[:m]


def intersect(a: np.ndarray, b: np.ndarray) -> tuple[np.ndarray, np.ndarray] | None:
    """(indices into a, indices into b) of the sorted-unique intersection."""
    if min(len(a), len(b)) < MIN_BATCH:
        return None
    lib = load()
    if lib is None:
        return None
    aa, bb = _as_i32(a), _as_i32(b)
    cap = min(len(aa), len(bb))
    ia = np.empty(cap, dtype=np.int64)
    ib = np.empty(cap, dtype=np.int64)
    m = lib.ytn_intersect(aa.ctypes.data_as(_i32p), ctypes.c_int64(len(aa)),
                          bb.ctypes.data_as(_i32p), ctypes.c_int64(len(bb)),
                          ia.ctypes.data_as(_i64p), ib.ctypes.data_as(_i64p))
    return ia[:m], ib[:m]


def alive_mask(docids: np.ndarray, dead_sorted: np.ndarray) -> np.ndarray | None:
    """Boolean mask of postings NOT tombstoned (dead_sorted ascending)."""
    if len(docids) < MIN_BATCH:
        return None
    lib = load()
    if lib is None:
        return None
    d, dd = _as_i32(docids), _as_i32(dead_sorted)
    out = np.empty(len(d), dtype=np.uint8)
    lib.ytn_remove_docids(d.ctypes.data_as(_i32p), ctypes.c_int64(len(d)),
                          dd.ctypes.data_as(_i32p), ctypes.c_int64(len(dd)),
                          out.ctypes.data_as(_u8p))
    return out.view(bool)
