"""Node health engine — rules, SLO burn rates, and a flight recorder.

The survey's coordinator-free P2P premise means no central control plane
ever notices a sick peer: each node must watch itself (SURVEY §1; the
reference's PerformanceQueues_p/PerformanceMemory_p pages are the
Java-era, human-polled version).  PRs 2–3 built the raw signals — trace
spine, `/metrics` counters, batcher cause buckets, result-cache and
round-trip counters — but nothing CONSUMED them: a degrading node looked
healthy until a human loaded a servlet.  This module is the consumer
(ISSUE 4 tentpole):

- **Declarative rules** evaluated by a switchboard busy-thread tick.
  Each rule reads only series that exist on the `/metrics` exposition
  (hygiene-tested: a rule referencing a dead series fails the build)
  and yields ``ok | warn | critical`` with a human-readable cause and
  the evidence values that justify it.
- **SLO burn rates.** The serving objective (p95 ≤ X ms, i.e. ≤ budget%
  of requests over X) is judged over a FAST window (the newest histogram
  rotation) and a SLOW window (all retained rotations): paging only when
  both burn — the standard multiwindow discipline that ignores blips but
  catches real burns fast ("Repeatability Corner Cases in Document
  Ranking": detection must compare distributions, not single samples).
- **Flight recorder.** Every tick appends the parsed `/metrics` sample
  set to a bounded ring; when any rule ENTERS ``critical`` (edge, rate
  limited) the ring is dumped as a JSONL incident file — snapshots,
  firing rules, histogram exemplar trace ids, and recent traces — so a
  postmortem never depends on someone having been watching.

The engine deliberately evaluates rules against the same exposition
pipeline the `/metrics` endpoint serves, rendered WITHOUT the
per-bucket histogram samples (no rule reads buckets, and ~100 bucket
lines per family would dominate the tick's cost): every counter, gauge
and histogram `_sum`/`_count` a rule can reference carries exactly the
value a concurrent scrape would see.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from . import faultinject, histogram, tracing

OK, WARN, CRITICAL = "ok", "warn", "critical"
_SEVERITY = {OK: 0, WARN: 1, CRITICAL: 2}

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})?)\s+(-?[0-9.eE+-]+)"
    r"(?:\s+#.*)?$")


def parse_exposition(text: str) -> dict:
    """Prometheus text -> {'family{labels}': value}.  Keys are the exact
    sample prefixes the exposition rendered (exemplar suffixes
    stripped), so rule series references are checked against reality."""
    out: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out


@dataclass
class RuleState:
    state: str = OK
    cause: str = ""
    since: float = 0.0
    evidence: dict = field(default_factory=dict)


@dataclass(frozen=True)
class Rule:
    """One detector: `series` lists every exposition sample the
    evaluator reads (the hygiene contract), `evaluate` maps the
    snapshot history to (state, cause, evidence)."""

    name: str
    description: str
    series: tuple
    evaluate: Callable


class RuleCtx:
    """What a rule may look at: the snapshot history (newest last), the
    windowed histograms, and the fleet digest table (ISSUE 5 — the
    fleet_* rules judge the MESH, not just this node)."""

    def __init__(self, history, trend_ticks: int, fleet=None):
        self._hist = history
        self.trend_ticks = trend_ticks
        self.fleet = fleet

    def value(self, key: str, default: float = 0.0) -> float:
        if not self._hist:
            return default
        return self._hist[-1][1].get(key, default)

    def ago(self, key: str, n: int, default: float = 0.0) -> float:
        """Value n ticks back (clamped to the oldest retained)."""
        if not self._hist:
            return default
        i = max(0, len(self._hist) - 1 - n)
        return self._hist[i][1].get(key, default)

    def delta(self, key: str, n: int | None = None) -> float:
        n = self.trend_ticks if n is None else n
        return self.value(key) - self.ago(key, n)

    def ticks(self) -> int:
        return len(self._hist)

    @staticmethod
    def hist(name: str):
        return histogram.get(name)


# ---------------------------------------------------------------------------
# the rule set
# ---------------------------------------------------------------------------

def build_rules(cfg) -> list:
    """The node's detectors.  Thresholds read config once at build time
    (the engine is rebuilt on config edits via `Switchboard` restart —
    the reference's model for performance knobs)."""
    g = cfg.get_float
    gi = cfg.get_int
    slo_ms = g("health.sloServingP95Ms", 250.0)
    budget = max(1e-6, g("health.sloBudgetPct", 5.0) / 100.0)
    min_qps = g("health.sloMinQps", 1.0)
    fast_crit = g("health.sloFastBurnCritical", 6.0)
    slow_crit = g("health.sloSlowBurnCritical", 3.0)
    stall_ticks = gi("health.stallRecoveryTicks", 3)
    backlog_warn = gi("health.backlogWarnDepth", 4)
    backlog_crit = gi("health.backlogCriticalDepth", 16)
    drops_crit = gi("health.logDropsCritical", 100)
    min_act = gi("health.cacheMinActivity", 50)

    def slo_serving(ctx: RuleCtx):
        h = ctx.hist("servlet.serving")
        # fast = the current slot + the last closed one: the current
        # slot alone is near-empty right after each rotation and would
        # flap the qps floor mid-burn
        frac_fast, n_fast = h.fraction_over(slo_ms, last=2)
        frac_slow, n_slow = h.fraction_over(slo_ms)
        qps_fast = n_fast / h.window_seconds(2)
        ev = {"slo_ms": slo_ms, "qps_fast": round(qps_fast, 3),
              "frac_over_fast": round(frac_fast, 4),
              "frac_over_slow": round(frac_slow, 4),
              "requests_windowed": n_slow}
        if qps_fast < min_qps:
            return OK, "below SLO traffic floor", ev
        fast_burn = frac_fast / budget
        slow_burn = frac_slow / budget
        ev["fast_burn"] = round(fast_burn, 2)
        ev["slow_burn"] = round(slow_burn, 2)
        if fast_burn >= fast_crit and slow_burn >= slow_crit:
            return CRITICAL, (
                f"serving SLO burning {fast_burn:.1f}x budget (fast) / "
                f"{slow_burn:.1f}x (slow): p95 objective {slo_ms}ms"), ev
        if fast_burn >= 1.0 and slow_burn >= 1.0:
            return WARN, (
                f"serving error budget burning at {slow_burn:.1f}x "
                f"sustainable rate"), ev
        return OK, "within SLO", ev

    _hits = 'yacy_device_serving_total{counter="rank_cache_hits"}'
    _served = 'yacy_device_serving_total{counter="queries_served"}'
    _stale = 'yacy_device_serving_total{counter="rank_cache_stale"}'
    _epoch = "yacy_device_arena_epoch"
    _stallkey = 'yacy_batch_timeouts_total{cause="worker_stall"}'
    _qin = 'yacy_batcher_queue_depth{queue="incoming"}'
    _qfl = 'yacy_batcher_queue_depth{queue="inflight"}'
    _drops = "yacy_log_dropped_records_total"
    _frontier = 'yacy_crawler_queue_depth{stack="local"}'
    _fetches = "yacy_crawler_fetch_ms_count"

    def cache_collapse(ctx: RuleCtx):
        dq = ctx.delta(_served)
        dh = ctx.delta(_hits)
        tot_q = ctx.value(_served)
        tot_h = ctx.value(_hits)
        longterm = tot_h / tot_q if tot_q > 0 else 0.0
        recent = dh / dq if dq > 0 else 0.0
        ev = {"recent_hit_ratio": round(recent, 4),
              "longterm_hit_ratio": round(longterm, 4),
              "queries_in_window": int(dq)}
        if dq < min_act or longterm < 0.2:
            return OK, "cache not load-bearing / low activity", ev
        if recent < 0.1 * longterm:
            return CRITICAL, (
                f"result-cache hit ratio collapsed: {recent:.0%} recent "
                f"vs {longterm:.0%} lifetime"), ev
        if recent < 0.25 * longterm:
            return WARN, (
                f"result-cache hit ratio degrading: {recent:.0%} recent "
                f"vs {longterm:.0%} lifetime"), ev
        return OK, "cache hit ratio steady", ev

    def stale_spike(ctx: RuleCtx):
        dq = ctx.delta(_served)
        ds = ctx.delta(_stale)
        de = ctx.delta(_epoch)
        ratio = ds / dq if dq > 0 else 0.0
        ev = {"stale_in_window": int(ds), "epoch_moves": int(de),
              "stale_ratio": round(ratio, 4),
              "queries_in_window": int(dq)}
        if dq < min_act or ratio <= 0.2:
            return OK, "stale rate nominal", ev
        if de > 0:
            return WARN, (
                f"stale spike ({ratio:.0%}) during arena-epoch churn "
                f"({int(de)} moves) — expected invalidation storm"), ev
        return CRITICAL, (
            f"stale rate {ratio:.0%} with NO epoch movement — "
            f"unexplained cache invalidation"), ev

    def backlog(ctx: RuleCtx):
        depth = ctx.value(_qin) + ctx.value(_qfl)
        before = (ctx.ago(_qin, ctx.trend_ticks)
                  + ctx.ago(_qfl, ctx.trend_ticks))
        ev = {"depth": int(depth), "depth_before": int(before),
              "incoming": int(ctx.value(_qin)),
              "inflight": int(ctx.value(_qfl))}
        growing = depth > before
        if depth >= backlog_crit and growing:
            return CRITICAL, (
                f"batcher backlog {int(depth)} and growing "
                f"(was {int(before)})"), ev
        if depth >= backlog_warn and growing:
            return WARN, (
                f"batcher queues growing: {int(before)} -> "
                f"{int(depth)}"), ev
        return OK, "queues draining", ev

    def worker_stall(ctx: RuleCtx):
        cur = ctx.value(_stallkey)
        recent = cur - ctx.ago(_stallkey, stall_ticks)
        ev = {"worker_stall_total": int(cur),
              "new_in_window": int(recent)}
        if recent > 0:
            return CRITICAL, (
                f"{int(recent)} worker_stall timeout(s) in the last "
                f"{stall_ticks} ticks — a kernel call is wedged"), ev
        return OK, "no recent stalls", ev

    def log_drops(ctx: RuleCtx):
        d = ctx.delta(_drops)
        ev = {"dropped_in_window": int(d),
              "dropped_total": int(ctx.value(_drops))}
        if d >= drops_crit:
            return CRITICAL, (
                f"{int(d)} log records dropped in the window — the "
                f"async log writer cannot keep up"), ev
        if d > 0:
            return WARN, f"{int(d)} log records dropped in the window", ev
        return OK, "no log drops", ev

    # -- fleet rules (ISSUE 5): the mesh view over gossiped digests ----------

    fleet_min_qps = g("health.fleetSloMinQps", 1.0)
    outlier_factor = g("health.fleetOutlierFactor", 3.0)
    outlier_min_mesh = gi("health.fleetOutlierMinSamples", 50)
    outlier_min_peer = gi("health.fleetOutlierMinPeerSamples", 20)

    def fleet_slo(ctx: RuleCtx):
        fl = ctx.fleet
        peers = fl.fresh() if fl is not None else []
        if not peers:
            return OK, "no fleet peers gossiping", {"peers": 0}
        counts = fl.merged_counts("servlet.serving")
        total = sum(counts)
        window_s = histogram.WINDOWS * histogram.ROTATE_EVERY_S
        qps = total / window_s
        frac = histogram.fraction_over_counts(counts, slo_ms)
        ev = {"peers": len(peers), "mesh_requests": total,
              "mesh_qps": round(qps, 3), "frac_over": round(frac, 4),
              "slo_ms": slo_ms,
              "mesh_p95_ms": round(
                  histogram.percentile_from_counts(counts, 0.95), 1)}
        if qps < fleet_min_qps:
            return OK, "below mesh SLO traffic floor", ev
        burn = frac / budget
        ev["burn"] = round(burn, 2)
        if burn >= slow_crit:
            return CRITICAL, (
                f"mesh serving SLO burning {burn:.1f}x budget across "
                f"{len(peers) + 1} nodes (p95 objective {slo_ms}ms)"), ev
        if burn >= 1.0:
            return WARN, (f"mesh error budget burning at {burn:.1f}x "
                          f"sustainable rate"), ev
        return OK, "mesh within SLO", ev

    def fleet_outlier(ctx: RuleCtx):
        fl = ctx.fleet
        peers = fl.fresh() if fl is not None else []
        if not peers:
            return OK, "no fleet peers gossiping", {"peers": 0}
        merged = fl.merged_counts("servlet.serving")
        total = sum(merged)
        ev = {"peers": len(peers), "mesh_requests": total}
        if total < outlier_min_mesh:
            return OK, "insufficient mesh traffic", ev
        mesh_p95 = histogram.percentile_from_counts(merged, 0.95)
        ev["mesh_p95_ms"] = round(mesh_p95, 2)
        rows = [(fl.my_hash, fl.local_counts("servlet.serving"))] \
            if fl.my_hash else []
        rows += [(e["peer"], e["hist"].get("servlet.serving"))
                 for e in peers]
        worst = None
        for phash, counts in rows:
            if not counts or sum(counts) < outlier_min_peer:
                continue        # absent/thin family: no verdict, not zero
            # leave-one-out baseline: judge the peer against the REST of
            # the mesh, not a merged p95 its own samples already drag —
            # a high-traffic outlier would otherwise mask itself (its
            # samples set the merged tail, so local/merged stays ~1x)
            rest = [max(0, m - c) for m, c in zip(merged, counts)]
            if sum(rest) < outlier_min_peer:
                continue        # no baseline to judge against
            rest_p95 = histogram.percentile_from_counts(rest, 0.95)
            p95 = histogram.percentile_from_counts(counts, 0.95)
            if p95 > outlier_factor * rest_p95 \
                    and (worst is None or p95 > worst[1]):
                worst = (phash, p95, rest_p95)
        if worst is not None:
            ev["outlier_peer"] = worst[0]
            ev["outlier_p95_ms"] = round(worst[1], 2)
            ev["rest_p95_ms"] = round(worst[2], 2)
            return CRITICAL, (
                f"peer {worst[0]} drags the mesh tail: local p95 "
                f"{worst[1]:.0f}ms vs rest-of-mesh p95 {worst[2]:.0f}ms "
                f"(> {outlier_factor:g}x)"), ev
        return OK, "no peer outlier", ev

    def fleet_critical(ctx: RuleCtx):
        fl = ctx.fleet
        peers = fl.fresh() if fl is not None else []
        crit = sorted(e["peer"] for e in peers if e.get("health") == 2)
        stalls = sorted(e["peer"] for e in peers
                        if e.get("rules", {}).get("worker_stall") == 2)
        ev = {"peers": len(peers), "critical_peers": len(crit),
              "worker_stall_peers": len(stalls),
              "names": ",".join(sorted(set(crit + stalls))[:8])}
        if not peers:
            return OK, "no fleet peers gossiping", ev
        if stalls:
            return CRITICAL, (
                f"{len(stalls)} peer(s) report a wedged kernel "
                f"(worker_stall): {ev['names']}"), ev
        if len(crit) * 2 >= len(peers):
            return CRITICAL, (f"{len(crit)}/{len(peers)} fleet peers "
                              f"critical: {ev['names']}"), ev
        if crit:
            return WARN, (f"{len(crit)} fleet peer(s) critical: "
                          f"{ev['names']}"), ev
        return OK, "fleet peers healthy", ev

    # -- crash-consistency / device-loss rules (ISSUE 10) --------------------

    _corr_keys = tuple(
        f'yacy_storage_corruption_total{{kind="{k}",action="{a}"}}'
        for k, a in (("run", "quarantined"), ("run", "error"),
                     ("segment", "error"),
                     ("segment", "served_degraded"),
                     ("journal", "error")))
    _lost = "yacy_device_lost"
    _recov = 'yacy_device_loss_total{event="recoveries"}'
    _losses = 'yacy_device_loss_total{event="losses"}'

    def storage_corruption(ctx: RuleCtx):
        total = sum(ctx.value(k) for k in _corr_keys)
        # counters are process-local: on the FIRST tick everything on
        # record happened since start — a delta would read 0 and the
        # critical edge (and its incident) would never fire for
        # corruption detected before the engine's first evaluation
        new = total if ctx.ticks() <= 1 \
            else sum(ctx.delta(k) for k in _corr_keys)
        ev = {"new_in_window": int(new), "total": int(total),
              "by_kind": {k.split('kind="')[1].split('"')[0]
                          + "/" + k.split('action="')[1].split('"')[0]:
                          int(ctx.value(k)) for k in _corr_keys
                          if ctx.value(k)}}
        if new > 0:
            # the critical EDGE dumps a flight-recorder incident — the
            # corruption's evidence (which kind, which action) is in the
            # record even if the operator looks hours later
            return CRITICAL, (
                f"{int(new)} storage corruption event(s) detected in "
                f"the window (checksum mismatch / quarantine)"), ev
        if total > 0:
            return OK, (f"no new corruption ({int(total)} historical "
                        f"event(s) on record)"), ev
        return OK, "no storage corruption detected", ev

    def device_loss(ctx: RuleCtx):
        lost = ctx.value(_lost)
        recovered = ctx.delta(_recov)
        ev = {"device_lost": int(lost),
              "losses_total": int(ctx.value(_losses)),
              "recoveries_total": int(ctx.value(_recov)),
              "recovered_in_window": int(recovered)}
        if lost >= 1:
            return CRITICAL, (
                "device LOST: queries served via counted host fallback "
                "(X-YaCy-Degraded: device-loss); background rebuild "
                "re-uploading the hot tier"), ev
        if recovered > 0:
            return WARN, (f"device serving resumed after rebuild "
                          f"({int(recovered)} recovery(ies) in the "
                          f"window)"), ev
        return OK, "device serving", ev

    # -- crawl-to-searchable SLO (ISSUE 13a) ---------------------------------

    ingest_p95_ms = g("health.ingestSearchableP95Ms", 2000.0)
    ingest_budget = max(1e-6,
                        g("health.ingestSloBudgetPct", 5.0) / 100.0)
    ingest_min_docs = gi("health.ingestSloMinDocs", 10)

    def ingest_slo(ctx: RuleCtx):
        """Freshness burn rate: the fraction of documents whose
        crawl-to-searchable wall exceeded the objective, judged with
        the same fast/slow multiwindow discipline as slo_serving_p95.
        Backpressure needs no separate term — a writer's blocked wall
        lands inside its documents' own searchable latency by
        construction (rwi.wait_capacity runs before the store)."""
        h = ctx.hist("ingest.searchable")
        frac_fast, n_fast = h.fraction_over(ingest_p95_ms, last=2)
        frac_slow, n_slow = h.fraction_over(ingest_p95_ms)
        bp = ctx.hist("ingest.backpressure")
        _bpf, bp_n = bp.fraction_over(0.0)
        ev = {"objective_ms": ingest_p95_ms,
              "docs_fast": n_fast, "docs_windowed": n_slow,
              "frac_over_fast": round(frac_fast, 4),
              "frac_over_slow": round(frac_slow, 4),
              "backpressure_waits_windowed": bp_n}
        if n_fast < ingest_min_docs:
            return OK, "below ingest traffic floor", ev
        fast_burn = frac_fast / ingest_budget
        slow_burn = frac_slow / ingest_budget
        ev["fast_burn"] = round(fast_burn, 2)
        ev["slow_burn"] = round(slow_burn, 2)
        if fast_burn >= fast_crit and slow_burn >= slow_crit:
            return CRITICAL, (
                f"crawl-to-searchable SLO burning {fast_burn:.1f}x "
                f"budget (fast) / {slow_burn:.1f}x (slow): p95 "
                f"objective {ingest_p95_ms}ms — the write path cannot "
                f"keep the index fresh"), ev
        if fast_burn >= 1.0 and slow_burn >= 1.0:
            return WARN, (
                f"crawl-to-searchable budget burning at "
                f"{slow_burn:.1f}x sustainable rate"), ev
        return OK, "index freshness within SLO", ev

    def frontier_starvation(ctx: RuleCtx):
        def starving(i: int) -> bool:
            # at tick `i` ago: frontier empty while that tick still
            # fetched — the frontier isn't keeping the fetcher fed
            return (ctx.ago(_frontier, i) == 0
                    and ctx.ago(_fetches, i) - ctx.ago(_fetches, i + 1)
                    > 0)
        ev = {"frontier_local": int(ctx.value(_frontier)),
              "fetches_in_window": int(ctx.delta(_fetches))}
        # TWO consecutive starving ticks: a finished crawl legitimately
        # drains the frontier to 0 while its last fetches land, but its
        # fetching stops within one tick — only a crawl that KEEPS
        # fetching against an empty frontier is starving
        if ctx.ticks() >= 3 and starving(0) and starving(1):
            return WARN, (
                "crawler kept fetching across two ticks with an empty "
                "local frontier — crawl starving"), ev
        return OK, "frontier fed or crawl idle", ev

    return [
        Rule("slo_serving_p95",
             f"servlet serving p95 <= {slo_ms}ms at >= {min_qps} qps "
             "(fast <=60s / slow ~3min burn-rate windows)",
             ("yacy_servlet_serving_ms_count",), slo_serving),
        Rule("rank_cache_collapse",
             "top-k result-cache hit ratio collapse vs lifetime",
             (_hits, _served), cache_collapse),
        Rule("stale_rate_spike",
             "cache stale-rate spike judged against arena-epoch churn",
             (_stale, _served, _epoch), stale_spike),
        Rule("batcher_backlog",
             "batcher incoming/in-flight queue growth trend",
             (_qin, _qfl), backlog),
        Rule("worker_stall",
             "batcher worker_stall timeouts (wedged kernel call)",
             (_stallkey,), worker_stall),
        Rule("log_drops",
             "async logging queue drops",
             (_drops,), log_drops),
        Rule("crawler_frontier_starvation",
             "active crawl with an empty local frontier",
             (_frontier, _fetches), frontier_starvation),
        Rule("ingest_slo_searchable",
             f"crawl-to-searchable p95 <= {ingest_p95_ms}ms over "
             f">= {ingest_min_docs} docs/window (fast/slow burn-rate "
             "windows; backpressure walls land inside the latency)",
             ("yacy_ingest_searchable_ms_count",
              "yacy_ingest_backpressure_ms_count"), ingest_slo),
        Rule("storage_corruption",
             "checksum-detected storage corruption (runs / segments / "
             "journals) — critical on any new event; the edge dumps a "
             "flight-recorder incident",
             _corr_keys, storage_corruption),
        Rule("device_loss",
             "device declared lost after a transfer-failure streak "
             "(host fallback serving, background rebuild)",
             (_lost, _recov, _losses), device_loss),
        Rule("fleet_slo_serving",
             f"mesh-wide serving SLO burn rate over MERGED peer digests "
             f"(p95 objective {slo_ms}ms; coordinator-free federation)",
             ("yacy_fleet_peers",
              'yacy_fleet_merged_latency_ms{family="servlet.serving",'
              'quantile="p95"}'), fleet_slo),
        Rule("fleet_peer_outlier",
             f"peer whose local serving p95 exceeds the merged mesh p95 "
             f"by > {outlier_factor:g}x (names the dragging seed)",
             ("yacy_fleet_peers",
              'yacy_fleet_merged_latency_ms{family="servlet.serving",'
              'quantile="p95"}'), fleet_outlier),
        Rule("fleet_critical_peers",
             "fleet peers whose digests report critical health or a "
             "wedged kernel (worker_stall)",
             ("yacy_fleet_peers", "yacy_fleet_peer_reported_critical"),
             fleet_critical),
    ]


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class HealthEngine:
    """Owns the rule set, the snapshot ring, and the incident dumper.
    Constructed cheaply at switchboard init; all work happens in
    `tick()` (driven by the `15_health` busy thread, or directly by
    tests/operators)."""

    def __init__(self, sb, incidents_dir: str | None = None):
        self.sb = sb
        cfg = sb.config
        self.rules = build_rules(cfg)
        self.trend_ticks = cfg.get_int("health.trendTicks", 6)
        self.cooldown_s = cfg.get_float("health.incidentCooldownS", 300.0)
        self.snapshots: deque = deque(
            maxlen=cfg.get_int("health.flightSnapshots", 240))
        self.snapshot_dump_max = cfg.get_int(
            "health.incidentSnapshotMax", 60)
        # DATA/HEALTH retention cap (ISSUE 5 satellite): incident writes
        # are rate-limited but the directory grew unboundedly — keep the
        # newest N files, delete older on every write
        self.incident_keep = cfg.get_int("health.incidentKeepFiles", 50)
        self.states: dict[str, RuleState] = {
            r.name: RuleState(since=time.time()) for r in self.rules}
        self.incidents: deque = deque(maxlen=32)
        self.incident_count = 0          # monotonic (the deque is a ring)
        self.tick_count = 0
        self.last_tick = 0.0
        self._last_incident_ts = 0.0
        self._lock = threading.Lock()
        self._dir = incidents_dir
        if incidents_dir:
            os.makedirs(incidents_dir, exist_ok=True)

    # -- evaluation ----------------------------------------------------------

    def _exposition(self) -> str:
        # bucket-free: no rule reads per-bucket samples, and rendering
        # ~100 bucket lines per family each tick would dominate the
        # tick's cost (the <2% --health-overhead budget)
        from ..server.servlets.monitoring import prometheus_text
        return prometheus_text(self.sb, include_buckets=False)

    def tick(self, now: float | None = None) -> str:
        """One evaluation pass: snapshot `/metrics`, evaluate every
        rule, drive the actuator engine on the fresh rule states, dump
        an incident on an ok/warn->critical edge (rate limited).
        Returns the overall state."""
        now = time.time() if now is None else now
        # idle histogram families must not freeze their windows (a
        # sticky SLO verdict after traffic stops): the tick drives
        # rotation for whatever recording's lazy rotation missed
        histogram.rotate_due()
        # straggler conviction pass (ISSUE 19 / ROADMAP 1c, read-only):
        # self-limits to one evaluation per conviction window, no-op on
        # nodes without a mesh timeline (empty scoreboard)
        from . import tailattr
        tailattr.CONVICTIONS.observe(now)
        # bucket-free exposition: the ring (and incident dumps) keep the
        # _sum/_count + counter/gauge granularity
        snap = parse_exposition(self._exposition())
        with self._lock:
            self.snapshots.append((now, snap))
            ctx = RuleCtx(list(self.snapshots), self.trend_ticks,
                          fleet=getattr(self.sb, "fleet", None))
            entered_critical = []
            for rule in self.rules:
                try:
                    state, cause, ev = rule.evaluate(ctx)
                except Exception as e:  # a broken rule must be VISIBLE
                    state, cause, ev = WARN, f"rule error: {e!r}", {}
                st = self.states[rule.name]
                if state != st.state:
                    if state == CRITICAL:
                        entered_critical.append(rule.name)
                    st.since = now
                st.state, st.cause, st.evidence = state, cause, ev
            self.tick_count += 1
            self.last_tick = now
            do_dump = entered_critical and \
                now - self._last_incident_ts >= self.cooldown_s
            if do_dump:
                self._last_incident_ts = now
        # actuators run on the JUST-evaluated rule states, outside the
        # engine lock (they take config/batcher locks of their own) and
        # BEFORE the incident dump — the incident that pages on a burn
        # must already name the ladder step the burn triggered (ISSUE 9)
        act = getattr(self.sb, "actuators", None)
        if act is not None:
            try:
                act.tick(now)
            except Exception:
                import logging
                logging.getLogger("health").warning(
                    "actuator tick failed", exc_info=True)
        if entered_critical:
            # whitebox deep capture (ISSUE 20c): the ok->critical edge
            # arms one bounded high-rate profiler window — the NEXT
            # incident (or servlet read) embeds what the process was
            # doing while the rule burned.  Rate-limited inside.
            from . import profiling
            profiling.trigger(f"health.{entered_critical[0]}")
        if do_dump:
            with self._lock:
                self._dump_incident_locked(now, entered_critical)
        return self.overall()

    def tick_job(self) -> bool:
        """BusyThread adapter: busy pacing while the node is unhealthy."""
        return self.tick() != OK

    def overall(self) -> str:
        worst = max((_SEVERITY[s.state] for s in self.states.values()),
                    default=0)
        return [OK, WARN, CRITICAL][worst]

    def status_value(self) -> int:
        """0 ok / 1 warn / 2 critical — the `health_status` gauge."""
        return _SEVERITY[self.overall()]

    def rule_table(self) -> list:
        """(name, description, state, cause, since, evidence) rows for
        the servlet and the exposition."""
        return [(r.name, r.description, self.states[r.name])
                for r in self.rules]

    # -- hygiene -------------------------------------------------------------

    def undefined_series(self) -> list:
        """Rule series references that do NOT resolve against the live
        exposition — must be empty (the no-dead-rules build gate)."""
        keys = set(parse_exposition(self._exposition()))
        missing = []
        for r in self.rules:
            for s in r.series:
                if s not in keys:
                    missing.append(f"{r.name}: {s}")
        return missing

    # -- flight recorder -----------------------------------------------------

    def _dump_incident_locked(self, now: float, entered: list) -> None:
        """Serialize the ring + firing rules + exemplars + recent traces
        as one JSONL incident (called under `_lock`, edge-triggered and
        rate-limited by the caller)."""
        # post-hoc join keys (ISSUE 19): a monotonic per-process
        # incident_seq (wall clocks skew across mesh processes; the
        # verdict engine orders by (pid, seq)) and the armed-fault
        # snapshot AT DUMP TIME — the incident names the injections
        # that were live when it fired, which is what lets a game-day
        # verdict match this incident to its scheduled fault
        seq = self.incident_count + 1
        armed = faultinject.snapshot()
        lines = [json.dumps({
            "kind": "incident", "ts": round(now, 3),
            "incident_seq": seq, "pid": os.getpid(),
            "armed_faults": armed,
            "entered_critical": entered,
            "rules": [{
                "name": name, "state": st.state, "cause": st.cause,
                "since": round(st.since, 3), "evidence": st.evidence,
            } for name, _d, st in self.rule_table()],
        })]
        snaps = list(self.snapshots)[-self.snapshot_dump_max:]
        for ts, samples in snaps:
            lines.append(json.dumps({
                "kind": "snapshot", "ts": round(ts, 3),
                "series": samples}))
        # tail forensics (ISSUE 15c): when a SERVING SLO rule is what
        # went critical, the incident embeds the windowed cause
        # histogram and the straggler scoreboard — so it reads "p95
        # burn, 71% collective_straggler mesh1" instead of "p95 burn"
        if any(r in ("slo_serving_p95", "fleet_slo_serving")
               for r in entered):
            from . import tailattr
            lines.append(json.dumps({
                "kind": "tail_causes",
                "window": tailattr.windowed_causes(),
                "verdicts": [v.to_json()
                             for v in tailattr.verdicts(10)]}))
            lines.append(json.dumps({
                "kind": "straggler_scoreboard",
                "rows": tailattr.scoreboard()}))
        # straggler convictions (ISSUE 19 / ROADMAP 1c): every recent
        # conviction edge rides the incident like actuator breadcrumbs
        # — the postmortem reads "mesh1 convicted over 2 windows" next
        # to the burn it explains
        from . import tailattr as _ta
        for crumb in _ta.conviction_breadcrumbs():
            lines.append(json.dumps(
                {"kind": "straggler_convicted", **crumb}))
        # whitebox profile (ISSUE 20c): the incident embeds the top
        # folded stacks + per-lock wait/hold table + the last triggered
        # deep capture — the postmortem reads WHAT the process was doing
        # next to the burn, like the cause histogram above reads WHY
        from . import profiling
        lines.append(json.dumps(
            {"kind": "profile", **profiling.report()}))
        # actuator breadcrumbs (ISSUE 9): the incident names every
        # actuation around the edge — which ladder rung, which tuning
        # step, which peers were avoided — so a postmortem reads the
        # defense next to the burn that triggered it
        act = getattr(self.sb, "actuators", None)
        if act is not None:
            for crumb in act.recent_breadcrumbs():
                lines.append(json.dumps({"kind": "actuator", **crumb}))
        for h in histogram.all_histograms():
            for ex in h.snapshot()["exemplars"]:
                if ex is not None:
                    lines.append(json.dumps({
                        "kind": "exemplar", "family": h.name,
                        "trace_id": ex[0], "value_ms": round(ex[1], 3),
                        "ts": round(ex[2], 3)}))
        for t in tracing.traces(20):
            lines.append(json.dumps({"kind": "trace", **t.to_json()}))
        body = "\n".join(lines) + "\n"
        name = f"incident-{int(now)}-{entered[0]}.jsonl"
        path = None
        if self._dir:
            path = os.path.join(self._dir, name)
            try:
                with open(path, "w", encoding="utf-8") as f:
                    f.write(body)
            except OSError:
                path = None   # a full disk must not kill the tick; the
                # in-memory copy below still serves the servlet download
            self._prune_incident_files()
        self.incident_count += 1
        self.incidents.append({
            "name": name, "ts": now, "seq": seq,
            "armed_faults": armed, "rules": list(entered),
            "path": path, "body": body})

    def _prune_incident_files(self) -> None:
        """Enforce the DATA/HEALTH retention cap: newest
        `health.incidentKeepFiles` incident files stay, older ones go
        (oldest-mtime first; name-embedded timestamps break ties)."""
        if not self._dir or self.incident_keep <= 0:
            return
        try:
            names = [f for f in os.listdir(self._dir)
                     if f.startswith("incident-") and f.endswith(".jsonl")]
            names.sort(key=lambda f: (
                os.path.getmtime(os.path.join(self._dir, f)), f))
            for f in names[:-self.incident_keep]:
                os.remove(os.path.join(self._dir, f))
        except OSError:
            return    # retention must never kill the tick; the next
            # successful write retries the prune

    def incident_body(self, name: str) -> str | None:
        """Download surface: by registry name only (never a caller
        path — no traversal)."""
        for inc in self.incidents:
            if inc["name"] == name:
                return inc["body"]
        return None
