"""Bounded best-N priority queue — the result-merge structure.

Capability equivalent of the reference's WeakPriorityBlockingQueue
(reference: source/net/yacy/cora/sorting/WeakPriorityBlockingQueue.java:43):
a fixed-capacity ordered container that keeps the best N elements by weight,
counts evictions ("misses"), supports blocking take with timeout, and keeps a
drained list so earlier elements remain addressable by index (the paging
path of a live search event re-reads them).

Implementation: two heaps over the same alive-entry set with lazy deletion —
a min-heap (worst-first, drives eviction when full) and a negated max-heap
(best-first, drives poll) — giving O(log n) put/poll under interleaved
streaming producers and consumers.

On the device side this structure collapses into batched top-k kernels
(ops/topk.py); this host-side variant is the fusion point where asynchronous
producers (local device results, remote peers) meet.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Generic, Optional, TypeVar

T = TypeVar("T")


class Element(Generic[T]):
    __slots__ = ("payload", "weight")

    def __init__(self, payload: T, weight: int):
        self.payload = payload
        self.weight = weight


class WeakPriorityQueue(Generic[T]):
    """Keeps the best `maxsize` elements; largest weight = best."""

    def __init__(self, maxsize: int):
        assert maxsize > 0
        self.maxsize = maxsize
        self._alive: dict[int, tuple[int, T]] = {}   # seq -> (weight, payload)
        self._worst: list[tuple[int, int]] = []       # min-heap (weight, seq)
        self._best: list[tuple[int, int]] = []        # min-heap (-weight, seq)
        self._seq = itertools.count()
        self._drained: list[Element[T]] = []
        self._misses = 0
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)

    # -- internal helpers (hold lock) ---------------------------------------

    def _prune_locked(self, heap: list[tuple[int, int]]) -> None:
        while heap and heap[0][1] not in self._alive:
            heapq.heappop(heap)

    def _evict_worst_locked(self) -> None:
        self._prune_locked(self._worst)
        if self._worst:
            _, seq = heapq.heappop(self._worst)
            del self._alive[seq]

    # -- producers -----------------------------------------------------------

    def put(self, payload: T, weight: int) -> bool:
        """Insert; returns False if the element was rejected (too weak)."""
        with self._not_empty:
            if len(self._alive) >= self.maxsize:
                self._prune_locked(self._worst)
                if self._worst and self._worst[0][0] >= weight:
                    self._misses += 1
                    return False
                self._evict_worst_locked()
                self._misses += 1
            seq = next(self._seq)
            self._alive[seq] = (weight, payload)
            heapq.heappush(self._worst, (weight, seq))
            heapq.heappush(self._best, (-weight, seq))
            self._not_empty.notify()
            return True

    # -- consumers -----------------------------------------------------------

    def _poll_locked(self) -> Optional[Element[T]]:
        self._prune_locked(self._best)
        if not self._best:
            return None
        _, seq = heapq.heappop(self._best)
        weight, payload = self._alive.pop(seq)
        el = Element(payload, weight)
        self._drained.append(el)
        return el

    def poll(self) -> Optional[Element[T]]:
        """Remove and return the best element, or None if empty."""
        with self._lock:
            return self._poll_locked()

    def take(self, timeout_s: float | None = None) -> Optional[Element[T]]:
        """Blocking poll: wait up to timeout for an element."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._not_empty:
            while not self._alive:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                if not self._not_empty.wait(remaining):
                    return None
            return self._poll_locked()

    def element(self, index: int, timeout_s: float | None = None) -> Optional[Element[T]]:
        """The index'th best element ever drained; drains more as needed."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._not_empty:
            while len(self._drained) <= index:
                if self._alive:
                    self._poll_locked()
                    continue
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                if not self._not_empty.wait(remaining):
                    return None
            return self._drained[index]

    # -- introspection -------------------------------------------------------

    def peek_weight(self) -> Optional[int]:
        with self._lock:
            self._prune_locked(self._best)
            return -self._best[0][0] if self._best else None

    def size_queue(self) -> int:
        with self._lock:
            return len(self._alive)

    def size_drained(self) -> int:
        with self._lock:
            return len(self._drained)

    def size_available(self) -> int:
        with self._lock:
            return len(self._alive) + len(self._drained)

    @property
    def misses(self) -> int:
        with self._lock:
            return self._misses

    def is_empty(self) -> bool:
        return self.size_queue() == 0

    def list_all(self) -> list[Element[T]]:
        """Drain everything and return drained history (ranked order)."""
        with self._lock:
            while self._alive:
                self._poll_locked()
            return list(self._drained)
