"""Async bounded logging — the ConcurrentLog analog.

Capability equivalent of the reference's logging subsystem (reference:
source/net/yacy/cora/util/ConcurrentLog.java:48-60,356 — a bounded
500-entry queue drained by ONE writer thread, so hot paths never block
on disk IO; configured at startup from DATA/LOG, yacy.java:176-188).

Built on the stdlib pieces that implement exactly that shape: every
logger publishes through a QueueHandler into a bounded queue; a single
QueueListener thread writes to a rotating file under DATA/LOG plus the
console. When the queue is full the record is DROPPED (the reference
blocks; dropping is the deliberate choice here — a stalled disk must
not back-pressure the crawl/search hot paths through the logger).
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import queue
import threading

QUEUE_SIZE = 500

_lock = threading.Lock()
_listener: logging.handlers.QueueListener | None = None
_dropped = 0


class _DroppingQueueHandler(logging.handlers.QueueHandler):
    """Dropping variant: enqueue_nowait, count what was lost."""

    def enqueue(self, record) -> None:
        global _dropped
        try:
            self.queue.put_nowait(record)
        except queue.Full:
            _dropped += 1


def setup(data_dir: str | None = None, level: int = logging.INFO,
          console: bool = True) -> logging.Logger:
    """Install the async pipeline on the root logger (idempotent;
    reconfigures on repeat calls). Returns the root logger."""
    global _listener
    root = logging.getLogger()
    with _lock:
        _teardown_locked(root)

        q: queue.Queue = queue.Queue(maxsize=QUEUE_SIZE)
        sinks: list[logging.Handler] = []
        if data_dir:
            logdir = os.path.join(data_dir, "LOG")
            os.makedirs(logdir, exist_ok=True)
            fh = logging.handlers.RotatingFileHandler(
                os.path.join(logdir, "yacy.log"),
                maxBytes=4 << 20, backupCount=5, encoding="utf-8")
            fh.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname).1s %(name)s %(message)s"))
            sinks.append(fh)
        if console:
            ch = logging.StreamHandler()
            ch.setFormatter(logging.Formatter(
                "%(levelname).1s %(name)s %(message)s"))
            sinks.append(ch)

        root.addHandler(_DroppingQueueHandler(q))
        root.setLevel(level)
        _listener = logging.handlers.QueueListener(
            q, *sinks, respect_handler_level=True)
        _listener.start()
    return root


def _teardown_locked(root: logging.Logger) -> None:
    """Stop the listener, close its sinks, detach the queue handler —
    no leaked file descriptors on reconfigure, and no records silently
    vanishing into an undrained queue after shutdown (late log calls
    fall back to logging's lastResort stderr handler)."""
    global _listener
    if _listener is not None:
        _listener.stop()
        for sink in _listener.handlers:
            try:
                sink.close()
            except (OSError, ValueError):
                pass  # sink already closed or its fd gone at teardown
        _listener = None
    for h in list(root.handlers):
        root.removeHandler(h)
        if isinstance(h, _DroppingQueueHandler):
            h.close()


def shutdown() -> None:
    """Drain the queue, stop the writer thread, close sinks, detach."""
    with _lock:
        _teardown_locked(logging.getLogger())


def dropped_count() -> int:
    """Records lost to the bounded queue (observability surface)."""
    return _dropped


def get(name: str) -> logging.Logger:
    """Named logger (the ConcurrentLog.logger(name) surface)."""
    return logging.getLogger(name)
