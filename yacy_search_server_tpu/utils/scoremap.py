"""Score maps — counting maps behind facets, navigators and authority scores.

Equivalent capability to the reference's score-map family (reference:
source/net/yacy/cora/sorting/ConcurrentScoreMap.java, ClusteredScoreMap.java,
OrderedScoreMap.java). One thread-safe implementation covers all three roles;
iteration order is produced on demand (Python's sort is cheap relative to the
map sizes these hold: facet dimensions, host counts, top words).
"""

from __future__ import annotations

import threading
from typing import Generic, Hashable, Iterator, TypeVar

K = TypeVar("K", bound=Hashable)


class ScoreMap(Generic[K]):
    def __init__(self):
        self._map: dict[K, int] = {}
        self._lock = threading.Lock()

    def inc(self, key: K, amount: int = 1) -> int:
        with self._lock:
            v = self._map.get(key, 0) + amount
            self._map[key] = v
            return v

    def dec(self, key: K, amount: int = 1) -> int:
        return self.inc(key, -amount)

    def set(self, key: K, score: int) -> None:
        with self._lock:
            self._map[key] = score

    def get(self, key: K) -> int:
        with self._lock:
            return self._map.get(key, 0)

    def delete(self, key: K) -> int:
        with self._lock:
            return self._map.pop(key, 0)

    def contains(self, key: K) -> bool:
        with self._lock:
            return key in self._map

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def is_empty(self) -> bool:
        return len(self) == 0

    def clear(self) -> None:
        with self._lock:
            self._map.clear()

    def max_score(self) -> int:
        with self._lock:
            return max(self._map.values(), default=0)

    def total(self) -> int:
        with self._lock:
            return sum(self._map.values())

    def keys(self, up: bool = True) -> Iterator[K]:
        """Keys ordered by score (then key, for determinism)."""
        with self._lock:
            items = list(self._map.items())
        items.sort(key=lambda kv: (kv[1], str(kv[0])), reverse=not up)
        return iter(k for k, _ in items)

    def top(self, n: int) -> list[tuple[K, int]]:
        with self._lock:
            items = list(self._map.items())
        items.sort(key=lambda kv: (-kv[1], str(kv[0])))
        return items[:n]

    def items(self) -> list[tuple[K, int]]:
        with self._lock:
            return list(self._map.items())
