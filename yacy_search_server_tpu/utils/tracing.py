"""Distributed query tracing — the span spine across every serving layer.

Where `utils/eventtracker.py` records FLAT (label, count, duration)
tuples with no causality, this module carries a trace id through the
whole request path — servlet → SearchEvent → device/mesh batcher +
kernel → P2P fan-out → remote peer — so a slow query's wall can be
attributed to the stage that actually spent it ("Repeatability Corner
Cases in Document Ranking": tail behavior hides in stage interactions,
not stage averages; PAPERS.md).

Design rules (the EventTracker discipline, applied to spans):

- **Zero-alloc when disabled / untraced.** `span()` returns ONE shared
  no-op object unless tracing is enabled AND a trace is active on the
  calling context. A hot path outside any trace pays a contextvar read.
- **Context-carried.** The active (trace_id, span_id) rides a
  contextvar, so nested spans parent correctly across the synchronous
  call tree; explicit `attach()` / `span_in()` / `emit()` carry the
  context across thread handoffs (batcher items, pipeline stages,
  remote fan-out threads).
- **Bounded per-node ring.** Completed spans accumulate per trace in an
  insertion-ordered dict capped at `MAX_TRACES` traces of `MAX_SPANS`
  spans each; overflow increments drop counters instead of growing.
  Late spans (straggler peers merging after the root closed) still land
  in the ring — the same late-merge discipline as the result heap.
- **Wire-propagated.** `peers/protocol.py` stamps the active trace id
  into every RPC payload (`_trace`); `HttpTransport` moves it into the
  ``X-YaCy-Trace`` header, `server/httpd.py` parses it back, and
  `peers/server.py` opens the remote segment under the ORIGINATOR's
  trace id — so a scatter-gather search is one trace network-wide.
"""

from __future__ import annotations

import itertools
import json
import secrets
import threading
import time
from collections import OrderedDict
from contextvars import ContextVar
from dataclasses import dataclass, field

from . import histogram

# wire header carrying the trace id between peers (parsed in
# server/httpd.py for HTTP, peers/javawire.py part "xtrace" for the
# Java wire, payload key "_trace" for the in-band transports)
TRACE_HEADER = "X-YaCy-Trace"
PAYLOAD_KEY = "_trace"

MAX_TRACES = 256          # completed-trace ring size per node/process
MAX_SPANS = 1024          # spans retained per trace

_enabled = True
_lock = threading.Lock()
_ctx: ContextVar = ContextVar("yacy_trace_ctx", default=None)
_span_seq = itertools.count(1)

# traces dropped from the ring / spans dropped at the per-trace cap
dropped_traces = 0
dropped_spans = 0


@dataclass
class Span:
    """One completed span. `ts` is wall-clock start (epoch seconds),
    `dur_ms` the measured wall; `parent` is "" for trace-root and
    remote-segment roots."""

    sid: str
    parent: str
    name: str
    ts: float
    dur_ms: float
    attrs: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"sid": self.sid, "parent": self.parent, "name": self.name,
                "ts": round(self.ts, 6), "dur_ms": round(self.dur_ms, 3),
                **({"attrs": self.attrs} if self.attrs else {})}


@dataclass
class TraceRecord:
    trace_id: str
    root_name: str
    created: float
    spans: list = field(default_factory=list)
    done: bool = False
    dropped: int = 0

    def duration_ms(self) -> float:
        """Wall covered by the trace: root span duration when recorded,
        else the spread of whatever spans exist (remote segments)."""
        for s in self.spans:
            if s.parent == "" and s.name == self.root_name:
                return s.dur_ms
        if not self.spans:
            return 0.0
        t0 = min(s.ts for s in self.spans)
        t1 = max(s.ts + s.dur_ms / 1000.0 for s in self.spans)
        return (t1 - t0) * 1000.0

    def to_json(self) -> dict:
        return {"trace_id": self.trace_id, "root": self.root_name,
                "created": round(self.created, 6),
                "duration_ms": round(self.duration_ms(), 3),
                "dropped_spans": self.dropped,
                "spans": [s.to_json() for s in self.spans]}


_ring: "OrderedDict[str, TraceRecord]" = OrderedDict()


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def new_trace_id() -> str:
    return secrets.token_hex(8)


def valid_trace_id(tid) -> bool:
    """Inbound (wire) ids are untrusted: bound length + charset so a
    hostile peer cannot flood the ring with junk keys."""
    return (isinstance(tid, str) and 4 <= len(tid) <= 64
            and all(c.isalnum() or c in "-_" for c in tid))


def _new_sid() -> str:
    return f"s{next(_span_seq)}"


def _register(trace_id: str, root_name: str) -> TraceRecord:
    global dropped_traces
    with _lock:
        rec = _ring.get(trace_id)
        if rec is None:
            rec = TraceRecord(trace_id, root_name, time.time())
            _ring[trace_id] = rec
            while len(_ring) > MAX_TRACES:
                _ring.popitem(last=False)
                dropped_traces += 1
        return rec


def _record(trace_id: str, span: Span) -> None:
    global dropped_spans
    # every completed span ALSO lands in the windowed histogram for its
    # name, carrying its trace id as the exemplar — the one wiring point
    # that gives every traced wall (servlet roots, StageTimer bridge
    # spans, batcher spans, kernel emits, remote segments) a
    # distribution on /metrics with a link back to the waterfall
    # (ISSUE 4).  Recorded even when the ring drops the span: the
    # histogram measures the workload, the ring retains evidence.
    histogram.observe(span.name, span.dur_ms, trace_id)
    with _lock:
        rec = _ring.get(trace_id)
        if rec is None:
            # late span for an evicted trace: count it, don't resurrect
            dropped_spans += 1
            return
        if len(rec.spans) >= MAX_SPANS:
            rec.dropped += 1
            dropped_spans += 1
            return
        rec.spans.append(span)


# -- context -----------------------------------------------------------------

# trace id of the most recent ROOT span completed on this context: lets
# a caller that wraps traced work (httpd's servlet dispatch wall) stamp
# its histogram exemplar with the request's trace even though the trace
# closed inside the callee.  Per-context (thread-per-request), cleared
# by the wrapper before dispatch.
_last_root: ContextVar = ContextVar("yacy_last_root_trace", default=None)

# root-completion hooks (ISSUE 15): the tail-attribution engine
# registers here to classify every over-threshold serving root.  Kept
# as a registration surface (not an import) so bare tracing users pay
# nothing and there is no tracing -> tailattr import cycle.
_root_hooks: list = []


def add_root_hook(fn) -> None:
    """Register fn(trace_id, root_name, dur_ms), called after every
    ROOT span completes.  Idempotent per function object."""
    if fn not in _root_hooks:
        _root_hooks.append(fn)


def _fire_root_hooks(tid: str, name: str, dur_ms: float) -> None:
    for fn in _root_hooks:
        try:
            fn(tid, name, dur_ms)
        except Exception:  # lint: broad-except-ok(a broken classifier
            # hook must cost a log line, never the serving request
            # whose root span just closed)
            import logging
            logging.getLogger("tracing").warning(
                "root hook failed for %s", name, exc_info=True)


def last_trace_id() -> str | None:
    """Trace id of the most recent root span completed on this context."""
    return _last_root.get()


def clear_last_trace_id() -> None:
    _last_root.set(None)


def current() -> tuple[str, str] | None:
    """The active (trace_id, span_id), or None."""
    return _ctx.get()


def current_trace_id() -> str | None:
    ctx = _ctx.get()
    return ctx[0] if ctx else None


def attach(ctx: tuple[str, str] | None):
    """Set the active context (cross-thread handoff); returns the token
    for `detach`."""
    return _ctx.set(ctx)


def detach(token) -> None:
    _ctx.reset(token)


# -- span context managers ---------------------------------------------------

class _NoopSpan:
    """Shared do-nothing span: the zero-alloc path when tracing is off
    or no trace is active."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass


_NOOP = _NoopSpan()


class _LiveSpan:
    __slots__ = ("_tid", "_sid", "_parent", "_name", "_attrs",
                 "_t0", "_ts", "_token", "_root", "_end_trace")

    def __init__(self, tid: str, parent: str, name: str, attrs: dict,
                 root: bool = False, end_trace: bool = False):
        self._tid = tid
        self._sid = _new_sid()
        self._parent = parent
        self._name = name
        self._attrs = attrs
        self._root = root
        self._end_trace = end_trace

    def __enter__(self):
        self._ts = time.time()
        self._t0 = time.perf_counter()
        self._token = _ctx.set((self._tid, self._sid))
        return self

    def __exit__(self, etype, exc, tb):
        _ctx.reset(self._token)
        if etype is not None:
            self._attrs["error"] = etype.__name__
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        _record(self._tid, Span(
            self._sid, self._parent, self._name, self._ts,
            dur_ms, self._attrs))
        if self._root:
            _last_root.set(self._tid)
            _fire_root_hooks(self._tid, self._name, dur_ms)
        if self._end_trace:
            with _lock:
                rec = _ring.get(self._tid)
                if rec is not None:
                    rec.done = True
        return False

    def set(self, **attrs) -> None:
        self._attrs.update(attrs)

    @property
    def ctx(self) -> tuple[str, str]:
        return (self._tid, self._sid)


def trace(name: str, trace_id: str | None = None, **attrs):
    """Root span: starts a new trace (and registers it in the ring).
    If a trace is already active on this context, degrades to a child
    span — one request is one trace, however the layers nest."""
    if not _enabled:
        return _NOOP
    cur = _ctx.get()
    if cur is not None:
        return _LiveSpan(cur[0], cur[1], name, attrs)
    tid = trace_id or new_trace_id()
    _register(tid, name)
    return _LiveSpan(tid, "", name, attrs, root=True, end_trace=True)


def span(name: str, **attrs):
    """Child span under the active trace; no-op (shared object, zero
    alloc) when tracing is off or no trace is active."""
    if not _enabled:
        return _NOOP
    cur = _ctx.get()
    if cur is None:
        return _NOOP
    return _LiveSpan(cur[0], cur[1], name, attrs)


def span_in(ctx: tuple[str, str] | None, name: str, **attrs):
    """Child span under an EXPLICIT context (cross-thread handoff:
    pipeline entries, batcher items, remote fan-out threads). The
    context is attached for the span's duration so nested spans and the
    profiler bridge parent correctly."""
    if not _enabled or ctx is None:
        return _NOOP
    return _LiveSpan(ctx[0], ctx[1], name, attrs)


def attached(ctx: tuple[str, str] | None):
    """Attach a context for a block WITHOUT recording a span of its own
    — for code that already times itself through a bridged surface
    (StageTimer): the bridge's span lands under `ctx`, and nothing is
    double-recorded."""
    return _Attached(ctx)


class _Attached:
    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        self._token = _ctx.set(self._ctx)
        return self

    def __exit__(self, *exc):
        _ctx.reset(self._token)
        return False


def remote_trace(trace_id: str, name: str, **attrs):
    """Server side of wire propagation: open THIS node's segment of a
    trace that originated elsewhere. Registers the originator's trace id
    in the local ring (so the segment is inspectable here too) and roots
    a span under it."""
    if not _enabled or not valid_trace_id(trace_id):
        return _NOOP
    _register(trace_id, name)
    return _LiveSpan(trace_id, "", name, attrs)


def emit(name: str, dur_ms: float, ctx: tuple[str, str] | None = None,
         ts: float | None = None, **attrs) -> None:
    """Record an already-measured wall as a completed span — the bridge
    for timings taken elsewhere (the roofline profiler's kernel walls,
    the batcher's per-dispatch walls). Uses the active context unless an
    explicit one is given; silently a no-op outside any trace."""
    if not _enabled:
        return
    c = ctx if ctx is not None else _ctx.get()
    if c is None:
        return
    if ts is None:
        ts = time.time() - dur_ms / 1000.0
    _record(c[0], Span(_new_sid(), c[1], name, ts, dur_ms, attrs))


# -- pipeline (begin/end across async stages) --------------------------------

class PipelineTrace:
    """Explicit begin/end trace handle for work that flows through
    queue-decoupled stages (the 4-stage indexing pipeline): the handle
    travels on the work item, each stage opens `span_in(handle.ctx,...)`,
    and the last stage (or a drop) calls `end()`."""

    __slots__ = ("tid", "sid", "name", "attrs", "_ts", "_t0", "_done")

    def __init__(self, tid: str, name: str, attrs: dict):
        self.tid = tid
        self.sid = _new_sid()
        self.name = name
        self.attrs = attrs
        self._ts = time.time()
        self._t0 = time.perf_counter()
        self._done = False

    @property
    def ctx(self) -> tuple[str, str]:
        return (self.tid, self.sid)

    def end(self, **attrs) -> None:
        if self._done:
            return
        self._done = True
        self.attrs.update(attrs)
        _record(self.tid, Span(
            self.sid, "", self.name, self._ts,
            (time.perf_counter() - self._t0) * 1000.0, self.attrs))
        with _lock:
            rec = _ring.get(self.tid)
            if rec is not None:
                rec.done = True


def begin(name: str, **attrs) -> PipelineTrace | None:
    """Start a detached trace (see PipelineTrace); None when disabled —
    callers pass the handle around and every span_in(None, ...) is
    free."""
    if not _enabled:
        return None
    t = PipelineTrace(new_trace_id(), name, attrs)
    _register(t.tid, name)
    return t


# -- reading -----------------------------------------------------------------

def traces(n: int = 50) -> list[TraceRecord]:
    """Most recent `n` traces, newest first."""
    with _lock:
        recs = list(_ring.values())
    return recs[::-1][:max(0, n)]


def get_trace(trace_id: str) -> TraceRecord | None:
    with _lock:
        return _ring.get(trace_id)


def clear() -> None:
    global dropped_traces, dropped_spans
    with _lock:
        _ring.clear()
        dropped_traces = 0
        dropped_spans = 0


def export_jsonl(n: int = 50) -> str:
    """Recent traces as JSONL, one trace per line (the export surface
    Performance_Trace_p serves with format=jsonl)."""
    return "\n".join(json.dumps(t.to_json()) for t in traces(n))


# -- cross-peer trace assembly (ISSUE 5) -------------------------------------
#
# A distributed search is ONE trace id network-wide (the wire
# propagation above), but each peer's spans live in ITS ring: the
# originator sees an opaque `peers.remotesearch` gap where the remote
# work happened.  The `tracefetch` wire endpoint (peers/server.py)
# serves a trace's local segment by id; the originator merges fetched
# segments back into its record (P2PNode.assemble_trace), and
# Performance_Trace_p renders the full distributed waterfall.

def trace_segment(trace_id: str,
                  max_spans: int = MAX_SPANS) -> dict | None:
    """This node's retained segment of a trace, wire-serializable (the
    server side of the `tracefetch` endpoint).  `truncated` counts
    spans NOT shipped (ring-side drops + any cap applied here): an
    assembled waterfall must be able to say it is incomplete rather
    than silently omit the tail."""
    with _lock:
        rec = _ring.get(trace_id)
        if rec is None:
            return None
        return {"trace_id": rec.trace_id, "root": rec.root_name,
                "truncated": rec.dropped
                + max(0, len(rec.spans) - max_spans),
                "spans": [s.to_json() for s in rec.spans[:max_spans]]}


def merge_remote_spans(trace_id: str, spans, source: str) -> int:
    """Merge a fetched remote segment into the local ring; returns the
    number of spans actually added.

    Dedup + collision rules: a span whose (sid, name, start) already
    exists locally is the SAME span seen through a co-hosted ring and is
    skipped; a colliding sid with different content (two processes both
    count spans from s1) is renamed under a `source`-derived prefix,
    with parent links inside the fetched batch remapped consistently.
    Merged spans do NOT feed the windowed histograms — the remote node
    already observed them into its own, and they arrive in its digest.
    """
    global dropped_spans
    if not _enabled or not valid_trace_id(trace_id) \
            or not isinstance(spans, list) or not spans:
        return 0
    incoming = []
    for sj in spans[:MAX_SPANS]:
        if not isinstance(sj, dict):
            continue
        try:
            sid = str(sj["sid"])
            name = str(sj["name"])
            ts = float(sj.get("ts", 0.0))
            dur = float(sj.get("dur_ms", 0.0))
            parent = str(sj.get("parent", ""))
            attrs = sj.get("attrs")
            attrs = dict(attrs) if isinstance(attrs, dict) else {}
        except (KeyError, TypeError, ValueError):
            continue
        incoming.append((sid, parent, name, ts, dur, attrs))
    if not incoming:
        return 0
    root_name = next((n for _s, p, n, _t, _d, _a in incoming if p == ""),
                     incoming[0][2])
    rec = _register(trace_id, root_name)
    src = "".join(c for c in str(source) if c.isalnum())[:6] or "remote"
    merged = 0
    with _lock:
        existing = {s.sid: s for s in rec.spans}
        remap: dict[str, str] = {}
        fresh = []
        for sid, parent, name, ts, dur, attrs in incoming:
            ex = existing.get(sid)
            if ex is not None and ex.name == name \
                    and abs(ex.ts - ts) < 0.002:
                continue                    # same span, co-hosted ring
            nsid = sid if ex is None else f"{src}.{sid}"
            ex2 = existing.get(nsid)
            if ex2 is not None and ex2.name == name \
                    and abs(ex2.ts - ts) < 0.002:
                # merged by an earlier fetch (idempotence) — but still
                # record the rename: a NEW span in this batch may
                # parent on the colliding sid and must follow it to the
                # renamed copy, not the originator's unrelated local span
                remap[sid] = nsid
                continue
            remap[sid] = nsid
            attrs.setdefault("fetched_from", str(source))
            fresh.append(Span(nsid, parent, name, ts, dur, attrs))
        for s in fresh:
            s.parent = remap.get(s.parent, s.parent)
            if len(rec.spans) >= MAX_SPANS:
                rec.dropped += 1
                dropped_spans += 1
                continue
            rec.spans.append(s)
            merged += 1
    return merged


# the one nearest-rank convention across the observability layer lives
# in utils/histogram.py; this alias survives for the callers that
# learned it here (profiler, bench).  The per-stage p50/p95 summary
# (formerly stage_summary, a full ring walk per call) lives in
# histogram.stage_table now: every span feeds the windowed histograms
# at record time, so the table is maintained incrementally and covers
# untraced work too.
_pctl = histogram.pctl
