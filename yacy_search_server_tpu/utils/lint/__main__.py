"""CLI: ``python -m yacy_search_server_tpu.utils.lint``.

Exit 0 when the tree is clean against the committed baseline (and the
baseline has no stale entries); exit 1 otherwise.  ``--write-baseline``
pins the CURRENT findings as debt — for bootstrapping only; the merge
rule is that LINT_BASELINE.json may only shrink (BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m yacy_search_server_tpu.utils.lint",
        description="yacylint: single-parse multi-checker static "
                    "analysis over the package tree")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files to lint (default: the "
                         "whole package)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings + stats")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report raw findings, ignoring "
                         "LINT_BASELINE.json")
    ap.add_argument("--write-baseline", action="store_true",
                    help="pin current findings as the new baseline "
                         "(bootstrap only — baselines may only shrink)")
    ap.add_argument("--checker", action="append", default=None,
                    help="run only this checker id (repeatable)")
    args = ap.parse_args(argv)
    if args.write_baseline and (args.paths or args.checker):
        ap.error("--write-baseline requires a FULL run: a subset "
                 "baseline would silently delete every pinned entry "
                 "outside the subset")

    result = engine.run(rel_paths=args.paths or None,
                        only=set(args.checker) if args.checker else None)
    bl_path = engine.baseline_path()
    if args.write_baseline:
        engine.write_baseline(bl_path, result)
        print(f"wrote {len(result.findings)} finding(s) to {bl_path}")
        return 0
    if not args.no_baseline:
        result = engine.apply_baseline(result,
                                       engine.load_baseline(bl_path))
        if args.paths or args.checker:
            # a subset run never generates the findings behind the
            # out-of-scope baseline entries — only a FULL run can
            # judge staleness (the shrink-only rule)
            result.stale_baseline = []

    if args.json:
        print(json.dumps({
            "findings": [f.__dict__ for f in result.findings],
            "suppressed_by_baseline": len(result.suppressed),
            "stale_baseline": result.stale_baseline,
            "by_checker": result.by_checker(),
            "stats": result.stats,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        if result.stale_baseline:
            print(f"-- {len(result.stale_baseline)} stale baseline "
                  f"entr{'y' if len(result.stale_baseline) == 1 else 'ies'} "
                  f"(fixed findings still pinned): delete them from "
                  f"{engine.BASELINE_NAME} — baselines only shrink")
            for e in result.stale_baseline:
                print(f"   stale: {e['checker']}::{e['path']}:"
                      f"{e['line']}")
        n = len(result.findings)
        sup = len(result.suppressed)
        print(f"yacylint: {n} finding(s)"
              + (f", {sup} baselined" if sup else "")
              + f", {result.stats.get('files', 0)} files, "
              f"{len(engine.CHECKERS)} checkers")
    return 1 if (result.findings or result.stale_baseline) else 0


if __name__ == "__main__":
    sys.exit(main())
