"""yacylint core — one ``ast.parse`` per file feeding a checker pipeline.

The engine owns everything that is NOT a rule: file discovery, the
single-parse file contexts, the shared exemption grammar, the committed
baseline, and the runner that hands every registered checker the whole
parsed repo at once.  Checkers (utils/lint/checkers.py) are pure
functions over :class:`Repo` — they never re-read or re-parse a file,
so a full run is one parse pass over the package (~150 files, well
under a second; tier-1 cheap).

**Exemption grammar** (one grammar for every checker, so an exemption
audit is a single grep for ``# lint:``):

    # lint: <token>(reason)

where ``<token>`` is the checker's suppression token (``unlocked-ok``,
``blocking-ok``, ``tie-ok``, ``unbounded-ok``, ``counter-ok``,
``impure-ok``, ``broad-except-ok``, ``costmodel-ok``, ``oracle-ok``,
``trace-ok``) and ``reason`` is MANDATORY prose — an empty reason or an
unknown token is itself a finding.  The comment exempts the statement
it sits on (any line of a multi-line statement); checkers additionally
honor it on the enclosing ``def`` or ``with`` line where that is the
natural scope (e.g. one ``blocking-ok`` on a ``with`` covers the block).

**Baseline** (LINT_BASELINE.json at the repo root): pre-existing debt
is PINNED, never silently grown.  A finding matching a baseline entry
is suppressed; a baseline entry matching no finding is STALE and fails
the run (the "baseline may only shrink" merge rule — see BASELINE.md).

Jax-free by contract: this package imports only the stdlib, so the lint
run works in any interpreter — CI sandboxes, the kill−9 chaos children,
a laptop without the jax_graft toolchain (tests/test_lint.py pins it).
"""

from __future__ import annotations

import ast
import io
import json
import pathlib
import re
import tokenize
from dataclasses import dataclass, field

# repo root = three parents up from utils/lint/engine.py's package dir
REPO_ROOT = pathlib.Path(__file__).resolve().parents[3]
PACKAGE = "yacy_search_server_tpu"
BASELINE_NAME = "LINT_BASELINE.json"

# the one exemption grammar (satellite: a single grep audits them all);
# matched against real COMMENT tokens only (never string literals, so
# checker messages can quote the grammar), and the reason may continue
# across following comment lines until one ENDS with the closing paren
EXEMPT_START = re.compile(r"#\s*lint:\s*([a-z][a-z0-9-]*)\((.*)$")


@dataclass(frozen=True)
class Finding:
    """One checker hit: file:line, checker id, message."""

    checker: str
    path: str       # repo-relative posix path
    line: int
    message: str

    @property
    def key(self) -> str:
        """Stable identity for baseline matching."""
        return f"{self.checker}::{self.path}::{self.line}::{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


class FileContext:
    """One parsed source file: tree + lines + its lint exemptions."""

    def __init__(self, path: pathlib.Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        # line -> [(token, reason)]; blocks = (start_line, token, reason)
        self.exemptions: dict[int, list[tuple[str, str]]] = {}
        self.exemption_blocks: list[tuple[int, str, str]] = []
        # line -> (comment text, True when the line holds ONLY the
        # comment — an inline trailing comment anchors to its own
        # statement and must not bleed onto the next one)
        comments: dict[int, tuple[str, bool]] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    line, col = tok.start
                    alone = not self.lines[line - 1][:col].strip()
                    comments[line] = (tok.string, alone)
        except tokenize.TokenError:      # the file parsed; best effort
            pass
        done: set[int] = set()
        for ln in sorted(comments):
            if ln in done:
                continue
            text, alone = comments[ln]
            m = EXEMPT_START.search(text)
            if m is None:
                continue
            token, rest = m.group(1), m.group(2)
            start, spans, i = ln, [ln], ln
            # the reason runs until a comment line ENDING with the
            # closing paren (reasons may mention call() sites inside);
            # continuation lines must be comment-only
            while not rest.rstrip().endswith(")") and \
                    comments.get(i + 1, ("", False))[1]:
                i += 1
                spans.append(i)
                done.add(i)
                rest += " " + comments[i][0].lstrip("#").strip()
            rest = rest.rstrip()
            reason = rest[:-1].strip() if rest.endswith(")") \
                else ""      # unterminated: empty reason -> flagged
            # a comment-ONLY block also covers the next code line, so
            # a comment above a def/with/call anchors to it naturally;
            # an inline trailing comment covers only its own statement
            if alone:
                j = i        # 0-based scan from the line after the block
                while j < len(self.lines) and (
                        not self.lines[j].strip()
                        or self.lines[j].lstrip().startswith("#")):
                    j += 1
                if j < len(self.lines):
                    spans.append(j + 1)
            for s_ln in spans:
                self.exemptions.setdefault(s_ln, []).append(
                    (token, reason))
            self.exemption_blocks.append((start, token, reason))

    def exempt(self, tokens, lines) -> str | None:
        """The reason of the first exemption carrying one of `tokens`
        on any of `lines` (a finding line, the comment line just above
        it, or an enclosing def/with line — the checker decides which
        lines form the natural scope), else None."""
        if isinstance(tokens, str):
            tokens = (tokens,)
        for ln in lines:
            for tok, reason in self.exemptions.get(ln, ()):
                if tok in tokens and reason:
                    return reason
        return None

    def node_lines(self, node: ast.AST) -> list[int]:
        """Every source line a (possibly multi-line) statement spans.
        Comment-only exemption blocks above a statement are anchored by
        the parser's next-code-line extension, so the span itself is
        the whole scope — never the preceding line (an inline comment
        there belongs to the PREVIOUS statement)."""
        lo = getattr(node, "lineno", 0)
        hi = getattr(node, "end_lineno", lo) or lo
        return list(range(lo, hi + 1))


class Repo:
    """The parsed tree of every scanned file — the single-parse pass
    all checkers share."""

    def __init__(self, root: pathlib.Path, files: dict[str, FileContext],
                 parse_errors: list[Finding]):
        self.root = root
        self.files = files
        self.parse_errors = parse_errors

    def get(self, rel: str) -> FileContext | None:
        return self.files.get(rel)

    def under(self, *prefixes: str) -> list[FileContext]:
        """File contexts whose repo-relative path starts with any of
        the given posix prefixes, in sorted path order."""
        return [self.files[r] for r in sorted(self.files)
                if any(r.startswith(p) for p in prefixes)]

    def dict_literal_keys(self, rel: str, name: str) -> set[str]:
        """String keys of the module-level dict literal assigned to
        `name` in `rel` — the static (jax-free) view of registries like
        ops/roofline.KERNELS.  Missing file/assignment -> empty set."""
        ctx = self.get(rel)
        if ctx is None:
            return set()
        keys: set[str] = set()
        for node in ctx.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name and \
                        isinstance(value, ast.Dict):
                    for k in value.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            keys.add(k.value)
        return keys


# -- checker registry ---------------------------------------------------------

# id -> (tokens, fn(repo, stats) -> iterable[Finding], doc)
CHECKERS: dict[str, tuple[tuple[str, ...], object, str]] = {}


def checker(cid: str, *tokens: str):
    """Register a checker under `cid` with its exemption token(s); the
    first token is the canonical one shown in messages."""
    def deco(fn):
        CHECKERS[cid] = (tokens, fn, (fn.__doc__ or "").strip())
        return fn
    return deco


def known_tokens() -> set[str]:
    return {t for toks, _fn, _doc in CHECKERS.values() for t in toks}


# -- discovery + run ----------------------------------------------------------

def discover(root: pathlib.Path | None = None,
             rel_paths=None) -> Repo:
    """Parse the package tree (or an explicit rel-path subset) once."""
    root = pathlib.Path(root) if root else REPO_ROOT
    files: dict[str, FileContext] = {}
    errors: list[Finding] = []
    if rel_paths:
        paths = []
        for r in rel_paths:
            p = root / r
            if p.is_dir():
                paths.extend(sorted(p.rglob("*.py")))
            elif p.is_file():
                paths.append(p)
            else:
                # a typo'd CI path must not yield a false-clean exit 0
                errors.append(Finding(
                    "parse-error", pathlib.PurePosixPath(r).as_posix(),
                    1, "path does not exist (nothing was linted)"))
    else:
        paths = sorted((root / PACKAGE).rglob("*.py"))
    for p in paths:
        if "__pycache__" in p.parts or not p.is_file():
            continue
        rel = p.relative_to(root).as_posix()
        try:
            src = p.read_text(encoding="utf-8")
            files[rel] = FileContext(p, rel, src)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            line = getattr(e, "lineno", 1) or 1
            errors.append(Finding("parse-error", rel, line,
                                  f"file does not parse: {e}"))
    return Repo(root, files, errors)


def _exemption_findings(repo: Repo) -> list[Finding]:
    """The grammar polices itself: unknown tokens and empty reasons are
    findings (a typo'd token must not silently disable a checker)."""
    out = []
    tokens = known_tokens()
    for rel in sorted(repo.files):
        ctx = repo.files[rel]
        for ln, tok, reason in ctx.exemption_blocks:
            if tok not in tokens:
                out.append(Finding(
                    "exemption", rel, ln,
                    f"unknown exemption token {tok!r} (known: "
                    f"{', '.join(sorted(tokens))})"))
            elif not reason:
                out.append(Finding(
                    "exemption", rel, ln,
                    f"exemption {tok!r} carries no reason — the "
                    f"reason is the point"))
    return out


@dataclass
class LintResult:
    findings: list[Finding]
    stats: dict = field(default_factory=dict)
    # baseline bookkeeping (filled by apply_baseline)
    suppressed: list[Finding] = field(default_factory=list)
    stale_baseline: list[dict] = field(default_factory=list)

    def by_checker(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.checker] = out.get(f.checker, 0) + 1
        return dict(sorted(out.items()))


def run(root: pathlib.Path | None = None, rel_paths=None,
        only: set[str] | None = None) -> LintResult:
    """The whole pipeline: discover → parse once → every checker."""
    # import for side effect: registers the checker pipeline
    from . import checkers as _checkers  # noqa: F401
    repo = discover(root, rel_paths)
    findings: list[Finding] = list(repo.parse_errors)
    stats: dict = {"files": len(repo.files)}
    # exemption tally rides the same single parse pass (lint_report
    # renders it; a second discover() for it would double the work)
    tally: dict[str, int] = {}
    for ctx in repo.files.values():
        for _ln, tok, _reason in ctx.exemption_blocks:
            tally[tok] = tally.get(tok, 0) + 1
    stats["exemptions"] = dict(sorted(tally.items()))
    findings.extend(_exemption_findings(repo))
    for cid, (_tokens, fn, _doc) in CHECKERS.items():
        if only is not None and cid not in only:
            continue
        cstats: dict = {}
        findings.extend(fn(repo, cstats))
        if cstats:
            stats[cid] = cstats
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.message))
    return LintResult(findings, stats)


# -- baseline -----------------------------------------------------------------

def baseline_path(root: pathlib.Path | None = None) -> pathlib.Path:
    return (pathlib.Path(root) if root else REPO_ROOT) / BASELINE_NAME


def load_baseline(path: pathlib.Path) -> list[dict]:
    if not path.is_file():
        return []
    obj = json.loads(path.read_text(encoding="utf-8"))
    return list(obj.get("findings", []))


def apply_baseline(result: LintResult, entries: list[dict]) -> LintResult:
    """Split findings into (new, suppressed) against the baseline and
    record stale entries — an entry matching nothing MUST be deleted
    (the shrink-only rule), so it is surfaced, not ignored."""
    keys = {f"{e['checker']}::{e['path']}::{e['line']}::{e['message']}": e
            for e in entries}
    matched: set[str] = set()
    fresh, suppressed = [], []
    for f in result.findings:
        if f.key in keys:
            matched.add(f.key)
            suppressed.append(f)
        else:
            fresh.append(f)
    result.findings = fresh
    result.suppressed = suppressed
    result.stale_baseline = [e for k, e in keys.items()
                             if k not in matched]
    return result


def write_baseline(path: pathlib.Path, result: LintResult) -> None:
    entries = [{"checker": f.checker, "path": f.path, "line": f.line,
                "message": f.message}
               for f in result.findings + result.suppressed]
    obj = {
        "_policy": "Pinned pre-existing lint debt. This file may only "
                   "SHRINK: new findings are fixed or exempted inline "
                   "with a reasoned `# lint: <token>(reason)` comment, "
                   "never added here. A stale entry fails the run until "
                   "it is deleted.",
        "findings": entries,
    }
    path.write_text(json.dumps(obj, indent=2, sort_keys=False) + "\n",
                    encoding="utf-8")
