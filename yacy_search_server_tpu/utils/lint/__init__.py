"""yacylint — the whole-repo static-analysis engine (ISSUE 14).

One ``ast.parse`` per file feeds a registered checker pipeline: a
lockset race detector, a blocking-call-under-lock pass, the tie
discipline lint, unbounded-queue / counter-outside-lock lints, a jit
purity lint, and the migrated hygiene scanners (cost models, oracles,
broad excepts, servlet spans).  Findings are ``file:line [checker]
message`` records; pre-existing debt is pinned in LINT_BASELINE.json
(shrink-only); every suppression is one grammar —
``# lint: <token>(reason)`` — so an exemption audit is a single grep.

Run it::

    python -m yacy_search_server_tpu.utils.lint            # gate (CI)
    python -m yacy_search_server_tpu.utils.lint --json     # machine form
    python tools/lint_report.py                            # PR summary

Jax-free by contract: stdlib only, so the gate runs in any interpreter
(tests/test_lint.py pins this).
"""

from .engine import (  # noqa: F401
    BASELINE_NAME,
    CHECKERS,
    Finding,
    LintResult,
    Repo,
    apply_baseline,
    baseline_path,
    checker,
    discover,
    known_tokens,
    load_baseline,
    run,
    write_baseline,
)
from .checkers import named_kernels, roofline_registry  # noqa: F401
