"""yacylint checkers — the registered rule pipeline.

Each checker is a pure function over the single-parse :class:`Repo`
(engine.py), registered with its exemption token(s).  The first five
are the concurrency/invariant rules this subsystem exists for (the bug
classes multi-pass human review kept catching by hand); the rest are
the ad-hoc hygiene scanners from tests/test_code_hygiene.py migrated
onto the engine so the repo has ONE static-analysis pass, one exemption
grammar, and one baseline.

Checker ids (and their suppression tokens):

=====================  ==================  ===================================
id                     token               catches
=====================  ==================  ===================================
``lockset``            ``unlocked-ok``     a majority-lock-guarded attribute
                                           read/written without the lock
``lock-blocking``      ``blocking-ok``     device transfers / HTTP / fsync /
                                           sleep lexically under a held lock
``tie-discipline``     ``tie-ok``          single-key sort/top-k in fusion
                                           paths (score DESC, docid ASC rule)
``counter-lock``       ``counter-ok``      a counter cohort mutated off the
                                           lock its siblings hold
``unbounded-queue``    ``unbounded-ok``    queue.Queue() with no maxsize
``jit-purity``         ``impure-ok``       time/random/set-iteration inside a
                                           jit-reachable kernel body (silent
                                           constant-folding hazards)
``broad-except``       ``broad-except-ok`` silent ``except Exception: pass``
``kernel-cost-model``  ``costmodel-ok``    jit/pallas kernel with no roofline
                                           cost model entry
``kernel-oracle``      ``oracle-ok``       serving kernel families (bp/ann)
                                           without a NumPy parity oracle, or
                                           dead oracle entries
``servlet-trace``      ``trace-ok``        wall-measuring servlet handlers
                                           outside the span spine
=====================  ==================  ===================================
"""

from __future__ import annotations

import ast

from .engine import Finding, Repo, checker

# -- shared AST helpers -------------------------------------------------------


def dotted(node: ast.AST) -> str:
    """'a.b.c' for a Name/Attribute chain ('' when not a plain chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        # e.g. fn().method or d["k"].attr — keep the attr tail so rules
        # matching the called method name still see it
        return "." + ".".join(reversed(parts))
    return ""


def _self_attr(node: ast.AST) -> str | None:
    """attr name when node is ``self.<attr>``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _is_lockish(expr: ast.AST) -> str | None:
    """The lock's display name when a with-item context expression looks
    like a lock (attribute/name containing 'lock' or 'mutex'), else
    None.  ``with self._lock:``, ``with _reg_lock:``, chained items and
    ``lk["lk"]``-style subscripts on lock dicts all count."""
    name = ""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Subscript) and \
            isinstance(expr.slice, ast.Constant) and \
            isinstance(expr.slice.value, str) and \
            "lk" == expr.slice.value:
        return "[lk]"
    low = name.lower()
    if "lock" in low or "mutex" in low:
        return name
    return None


def iter_defs(tree: ast.AST):
    """Every (qualname, FunctionDef) in the module, depth-first."""
    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield prefix + child.name, child
                yield from walk(child, prefix + child.name + ".")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, prefix + child.name + ".")
            else:
                yield from walk(child, prefix)
    yield from walk(tree, "")


def _decorator_is_jit(deco: ast.AST) -> bool:
    """@jax.jit / @jit / @partial(jax.jit, ...) /
    @functools.partial(jax.jit, ...) — the shapes the old hygiene regex
    recognized, now structurally."""
    d = dotted(deco)
    if d in ("jax.jit", "jit"):
        return True
    if isinstance(deco, ast.Call):
        f = dotted(deco.func)
        if f in ("jax.jit", "jit"):
            return True
        if f.endswith("partial") and deco.args:
            return dotted(deco.args[0]) in ("jax.jit", "jit")
    return False


def named_kernels(ctx) -> list[tuple[str, ast.FunctionDef]]:
    """(name, def) for every jit-decorated function plus every function
    whose body issues a ``pallas_call`` (pallas kernels are named by
    their host fn) — the engine-side replacement for the regex scanner
    the hygiene tests carried."""
    out = []
    for qual, fn in iter_defs(ctx.tree):
        if any(_decorator_is_jit(d) for d in fn.decorator_list):
            out.append((fn.name, fn))
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    dotted(node.func).split(".")[-1] == "pallas_call":
                out.append((fn.name, fn))
                break
    return out


# -- 1. lockset race detector -------------------------------------------------

# an attribute is "lock-guarded" once this many accesses hold the lock
# and that is at least GUARD_RATIO of all its non-__init__ accesses —
# below that the evidence is too thin to call the unguarded sites races
LOCKSET_MIN_GUARDED = 4
LOCKSET_GUARD_RATIO = 0.75


class _ClassLockScan(ast.NodeVisitor):
    """One class's access census: for every ``self.<attr>`` data access
    in a method body, whether a class lock was lexically held."""

    def __init__(self, lock_attrs: set[str]):
        self.lock_attrs = lock_attrs
        self.held: list[str] = []
        self.method = ""
        self.assume_held = False     # *_locked caller-holds convention
        # attr -> list[(lock_or_None, line, method, is_write)]
        self.accesses: dict[str, list] = {}
        # (attr, lock_or_None, line, method) per `self.X += ...` /
        # `self.X[...] += ...` — the counter-lock checker's census,
        # sharing this scan's lock tracking instead of duplicating it
        self.aug: list[tuple] = []

    def scan_method(self, m: ast.FunctionDef) -> None:
        self.method = m.name
        self.assume_held = m.name.endswith("_locked")
        for stmt in m.body:
            self.visit(stmt)

    # lock tracking ----------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        got = []
        for item in node.items:
            a = _self_attr(item.context_expr)
            if a in self.lock_attrs:
                got.append(a)
        self.held.extend(got)
        for stmt in node.body:
            self.visit(stmt)
        for _ in got:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested def is a deferred body (thread target, callback): it
        # does NOT inherit the lexical lock — scan it as unlocked.
        # Lambdas are different: they overwhelmingly run inline as
        # min/sorted key= callables, so they keep the lock state.
        saved, self.held = self.held, []
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    # access recording -------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        # `self.method(...)`: the func attribute is a call, not a data
        # access — but still walk the receiver chain and the arguments
        if _self_attr(node.func) is not None:
            pass
        else:
            self.visit(node.func)
        for a in node.args:
            self.visit(a)
        for kw in node.keywords:
            self.visit(kw.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        a = _self_attr(node.target)
        if a is None and isinstance(node.target, ast.Subscript):
            a = _self_attr(node.target.value)
        if a is not None and a not in self.lock_attrs:
            lock = self.held[-1] if self.held else (
                "(caller)" if self.assume_held else None)
            self.aug.append((a, lock, node.lineno, self.method))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        a = _self_attr(node)
        if a is not None and a not in self.lock_attrs:
            lock = self.held[-1] if self.held else (
                "(caller)" if self.assume_held else None)
            self.accesses.setdefault(a, []).append(
                (lock, node.lineno,
                 self.method, isinstance(node.ctx,
                                         (ast.Store, ast.Del))))
        self.generic_visit(node)


def _class_locks(cls: ast.ClassDef) -> set[str]:
    locks = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            f = dotted(node.value.func)
            # a Condition wraps (or is) a lock: `with self._not_empty:`
            # acquires it, so it guards exactly like a Lock; the
            # ObservedLock/ObservedRLock wrappers (utils/profiling.py,
            # ISSUE 20b) ARE locks and must keep guarding, or swapping
            # a raw lock for its observed twin would silently retire
            # every lockset/counter-lock rule over the class
            if f.split(".")[-1] in ("Lock", "RLock", "Condition",
                                    "ObservedLock", "ObservedRLock"):
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        locks.add(a)
    return locks


def _iter_classes(ctx):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            yield node


@checker("lockset", "unlocked-ok")
def check_lockset(repo: Repo, stats: dict):
    """Infer each class's lock-guarded attribute set from the census of
    ``with self._lock:``-dominated accesses, then flag the minority of
    sites that touch such an attribute without the lock."""
    findings = []
    classes = guarded_attrs = 0
    for ctx in repo.under("yacy_search_server_tpu/"):
        for cls in _iter_classes(ctx):
            locks = _class_locks(cls)
            if not locks:
                continue
            classes += 1
            scan = _ClassLockScan(locks)
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            for m in methods:
                if m.name in ("__init__", "__new__"):
                    continue     # pre-publication: the object is private
                scan.scan_method(m)
            for attr, recs in sorted(scan.accesses.items()):
                locked = [r for r in recs if r[0] is not None]
                bare = [r for r in recs if r[0] is None]
                total = len(recs)
                if len(locked) < LOCKSET_MIN_GUARDED or not bare or \
                        len(locked) / total < LOCKSET_GUARD_RATIO:
                    continue
                # majority lock by census (the one to name in the fix)
                by_lock: dict[str, int] = {}
                for lk, *_ in locked:
                    by_lock[lk] = by_lock.get(lk, 0) + 1
                lock = max(sorted(by_lock), key=by_lock.get)
                guarded_attrs += 1
                seen_lines = set()
                for _lk, line, method, is_write in bare:
                    if line in seen_lines:
                        continue
                    seen_lines.add(line)
                    node_lines = [line]
                    mdef = next((m for m in methods if m.name == method),
                                None)
                    if mdef is not None:
                        node_lines.append(mdef.lineno)
                    if ctx.exempt(("unlocked-ok",), node_lines):
                        continue
                    kind = "write" if is_write else "read"
                    findings.append(Finding(
                        "lockset", ctx.rel, line,
                        f"self.{attr} is guarded by self.{lock} at "
                        f"{len(locked)}/{total} sites, but "
                        f"{cls.name}.{method} {kind}s it without the "
                        f"lock — take the lock or annotate "
                        f"`# lint: unlocked-ok(reason)`"))
    stats["classes_with_locks"] = classes
    stats["guarded_attrs"] = guarded_attrs
    return findings


# -- 2. blocking call under a held lock ---------------------------------------

_BLOCKING_EXACT = {
    "time.sleep", "os.fsync", "os.fdatasync", "socket.create_connection",
    "jax.device_put", "jax.device_get", "device_put", "device_get",
    "urllib.request.urlopen", "urlopen",
}
_BLOCKING_TAIL = {
    "block_until_ready", "copy_to_host_async", "mesh_rpc", "fsync",
}
_BLOCKING_PREFIX = ("requests.", "subprocess.", "http.client.")


def _is_blocking_call(call: ast.Call) -> str | None:
    d = dotted(call.func)
    if d in _BLOCKING_EXACT:
        return d
    tail = d.split(".")[-1]
    if tail in _BLOCKING_TAIL:
        return d or tail
    if d.startswith(_BLOCKING_PREFIX):
        return d
    return None


class _LockBodyScan(ast.NodeVisitor):
    """Collect blocking calls lexically inside a with-lock body,
    skipping nested function bodies (deferred execution)."""

    def __init__(self):
        self.hits: list[tuple[str, int]] = []

    def visit_FunctionDef(self, node):
        return
    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        name = _is_blocking_call(node)
        if name:
            self.hits.append((name, node.lineno))
        self.generic_visit(node)


@checker("lock-blocking", "blocking-ok")
def check_lock_blocking(repo: Repo, stats: dict):
    """Flag device transfers, HTTP calls, fsync and sleeps lexically
    inside a ``with <lock>:`` body — the exact shape of the review-era
    bugs (multi-second transfers/merges stalling every other thread on
    the lock)."""
    findings = []
    regions = 0
    for ctx in repo.under("yacy_search_server_tpu/"):
        # enclosing def line per with-statement (the wider exemption
        # scope): map each with to the innermost def containing it
        encl: dict[int, int] = {}
        for qual, fn in iter_defs(ctx.tree):
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    encl[node.lineno] = fn.lineno
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locks = [nm for item in node.items
                     if (nm := _is_lockish(item.context_expr))]
            if not locks:
                continue
            regions += 1
            scan = _LockBodyScan()
            for stmt in node.body:
                scan.visit(stmt)
            for name, line in scan.hits:
                scope = [line, node.lineno]
                if node.lineno in encl:
                    scope.append(encl[node.lineno])
                if ctx.exempt(("blocking-ok",), scope):
                    continue
                findings.append(Finding(
                    "lock-blocking", ctx.rel, line,
                    f"blocking call {name}() inside `with "
                    f"{locks[0]}:` — every thread contending the lock "
                    f"stalls behind it; move it outside the critical "
                    f"section or annotate `# lint: blocking-ok(reason)`"))
    stats["lock_regions"] = regions
    return findings


# -- 3. tie discipline in fusion paths ----------------------------------------

TIE_SCOPES = ("yacy_search_server_tpu/ops/",
              "yacy_search_server_tpu/parallel/",
              "yacy_search_server_tpu/search/")


def _has_two_key_sort(fn: ast.FunctionDef) -> bool:
    """A lax.sort with num_keys>=2 or a multi-key np.lexsort anywhere
    in the function: the final two-key pass that pins (score DESC,
    docid ASC) no matter what an interior top-k prefilter did."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        tail = d.split(".")[-1]
        if tail == "sort" and ("lax" in d.split(".")):
            for kw in node.keywords:
                if kw.arg == "num_keys" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value >= 2:
                    return True
        if tail == "lexsort" and node.args and \
                isinstance(node.args[0], ast.Tuple) and \
                len(node.args[0].elts) >= 2:
            return True
    return False


@checker("tie-discipline", "tie-ok")
def check_tie_discipline(repo: Repo, stats: dict):
    """Every sort/top-k in the fusion paths must use the two-key form
    — (score, docid) via lax.sort num_keys>=2, a multi-key np.lexsort,
    or a kind='stable' argsort over docid-ordered rows — or carry a
    reasoned exemption (arxiv 1807.05798: unpinned ties flap rankings
    across runs, peers and cache entries)."""
    findings = []
    sites = 0
    for ctx in repo.under(*TIE_SCOPES):
        for qual, fn in iter_defs(ctx.tree):
            two_key = None      # computed lazily per function
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                tail = d.split(".")[-1]
                bad = None
                if tail == "top_k":
                    sites += 1
                    if two_key is None:
                        two_key = _has_two_key_sort(fn)
                    if not two_key:
                        bad = (f"{d}() is single-key (ties break by "
                               f"input position) and {qual} has no "
                               f"two-key final sort")
                elif tail == "argsort":
                    sites += 1
                    stable = any(kw.arg == "kind"
                                 and isinstance(kw.value, ast.Constant)
                                 and kw.value.value == "stable"
                                 for kw in node.keywords)
                    if not stable:
                        bad = (f"{d}() without kind='stable' — equal "
                               f"scores order arbitrarily")
                elif tail == "sort" and "lax" in d.split("."):
                    sites += 1
                    nk = next((kw.value.value for kw in node.keywords
                               if kw.arg == "num_keys"
                               and isinstance(kw.value, ast.Constant)),
                              1)
                    if nk < 2:
                        bad = (f"{d}() with num_keys={nk} — the "
                               f"two-key (score, docid) form is the "
                               f"pinned tie discipline")
                elif tail == "lexsort":
                    sites += 1
                    if not (node.args
                            and isinstance(node.args[0], ast.Tuple)
                            and len(node.args[0].elts) >= 2):
                        bad = f"{d}() with a single key"
                if bad is None:
                    continue
                scope = ctx.node_lines(node) + [fn.lineno]
                if ctx.exempt(("tie-ok",), scope):
                    continue
                findings.append(Finding(
                    "tie-discipline", ctx.rel, node.lineno,
                    bad + " — use the two-key form or annotate "
                          "`# lint: tie-ok(reason)`"))
    stats["sort_sites"] = sites
    return findings


# -- 4a. unbounded queues -----------------------------------------------------

_QUEUE_NAMES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}


def _literal_int(node: ast.AST):
    """The int value of a (possibly negated) literal, else None —
    ``Queue(-1)`` parses as UnaryOp(USub, Constant(1)) and means
    UNbounded, exactly like 0."""
    if isinstance(node, ast.Constant) and \
            isinstance(node.value, (int, float)):
        return node.value
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, ast.USub) and \
            isinstance(node.operand, ast.Constant) and \
            isinstance(node.operand.value, (int, float)):
        return -node.operand.value
    return None


@checker("unbounded-queue", "unbounded-ok")
def check_unbounded_queue(repo: Repo, stats: dict):
    """Every queue construction needs a maxsize bound: an unbounded
    queue of work (or of issued-but-unfetched device buffers) is
    unbounded memory — backpressure IS the cap.  Generalizes the old
    devstore/meshstore in-flight scan to the whole package."""
    findings = []
    sites = 0
    inflight_bounded = 0
    for ctx in repo.under("yacy_search_server_tpu/"):
        parents = {id(c): p for p in ast.walk(ctx.tree)
                   for c in ast.iter_child_nodes(p)}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            parts = d.split(".")
            if parts[-1] not in _QUEUE_NAMES:
                continue
            if len(parts) > 1 and parts[0] not in ("queue", "_queue"):
                continue    # e.g. multiprocessing.Queue — out of scope
            sites += 1
            bounded = False
            # queue semantics: maxsize <= 0 means INFINITE, so a
            # literal 0 or negative is unbounded; a dynamic expression
            # (Name, attribute) is trusted as a configured bound
            if parts[-1] != "SimpleQueue":      # never bounded
                for arg in (node.args[:1]
                            + [kw.value for kw in node.keywords
                               if kw.arg == "maxsize"]):
                    lit = _literal_int(arg)
                    bounded = lit is None or lit > 0
            # attribute the site for the anti-rot stat
            parent = parents.get(id(node))
            attr = None
            while parent is not None and attr is None:
                if isinstance(parent, ast.Assign):
                    for t in parent.targets:
                        attr = _self_attr(t) or attr
                    break
                if isinstance(parent, ast.AnnAssign):
                    attr = _self_attr(parent.target)
                    break
                parent = parents.get(id(parent))
            if attr == "_inflight" and bounded:
                inflight_bounded += 1
            if bounded:
                continue
            if ctx.exempt(("unbounded-ok",), ctx.node_lines(node)):
                continue
            findings.append(Finding(
                "unbounded-queue", ctx.rel, node.lineno,
                f"{d or parts[-1]}() without a maxsize bound — "
                f"unbounded queued work/memory; give it a bound or "
                f"annotate `# lint: unbounded-ok(reason)`"))
    stats["queue_sites"] = sites
    stats["inflight_bounded"] = inflight_bounded
    return findings


# -- 4b. counter mutated outside its cohort's lock ----------------------------

@checker("counter-lock", "counter-ok", "unlocked-ok")
def check_counter_lock(repo: Repo, stats: dict):
    """In a class whose numeric counters are incremented under a lock,
    EVERY counter increment must hold it: one counter drifting off the
    lock (the `_ms_lock` bug shape) silently corrupts the telemetry the
    health rules act on.  Unlike `lockset` this needs no per-attribute
    majority — the cohort's discipline is the evidence."""
    findings = []
    cohorts = 0
    for ctx in repo.under("yacy_search_server_tpu/"):
        for cls in _iter_classes(ctx):
            locks = _class_locks(cls)
            if not locks:
                continue
            # counters: numeric-initialized in __init__
            counters: set[str] = set()
            init = next((n for n in cls.body
                         if isinstance(n, ast.FunctionDef)
                         and n.name == "__init__"), None)
            if init is None:
                continue
            for node in ast.walk(init):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, (int, float)) and \
                        not isinstance(node.value.value, bool):
                    for t in node.targets:
                        a = _self_attr(t)
                        if a:
                            counters.add(a)
            if not counters:
                continue
            scan = _ClassLockScan(locks)
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            for m in methods:
                if m.name == "__init__":
                    continue
                scan.scan_method(m)
            aug = [rec for rec in scan.aug if rec[0] in counters]
            if not aug:
                continue
            if any(lk is not None for _a, lk, _l, _m in aug):
                cohorts += 1
            else:
                continue     # nothing guarded: lockset territory, not ours
            for attr, lk, line, method in aug:
                if lk is not None:
                    continue
                mdef = next((m for m in methods if m.name == method),
                            None)
                scope = [line] + ([mdef.lineno] if mdef else [])
                if ctx.exempt(("counter-ok", "unlocked-ok"), scope):
                    continue
                findings.append(Finding(
                    "counter-lock", ctx.rel, line,
                    f"counter self.{attr} incremented outside the "
                    f"lock its {cls.name} siblings hold — the "
                    f"unsynchronized += loses updates; take the lock "
                    f"or annotate `# lint: counter-ok(reason)`"))
    stats["counter_cohorts"] = cohorts
    return findings


# -- 5. jit purity ------------------------------------------------------------

_IMPURE_EXACT = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "datetime.datetime.now", "datetime.now", "datetime.datetime.utcnow",
}


def _impure_call(call: ast.Call) -> str | None:
    d = dotted(call.func)
    if d in _IMPURE_EXACT:
        return d
    if d.startswith(("np.random.", "numpy.random.", "random.")):
        return d
    return None


@checker("jit-purity", "impure-ok")
def check_jit_purity(repo: Repo, stats: dict):
    """Inside a jit-reachable kernel body, wall clocks, host RNGs and
    set-iteration are silent constant-folding hazards: the value is
    baked at trace time and never moves again.  Reachability is the
    jit-decorated defs plus module-local functions they call,
    transitively."""
    findings = []
    roots = 0
    for ctx in repo.under("yacy_search_server_tpu/"):
        defs = dict(iter_defs(ctx.tree))
        by_name: dict[str, list[str]] = {}
        for qual, fn in defs.items():
            by_name.setdefault(fn.name, []).append(qual)
        jit_roots = [qual for qual, fn in defs.items()
                     if any(_decorator_is_jit(d)
                            for d in fn.decorator_list)]
        roots += len(jit_roots)
        # module-local transitive closure over plain-name calls
        reach: set[str] = set()
        work = list(jit_roots)
        while work:
            qual = work.pop()
            if qual in reach:
                continue
            reach.add(qual)
            for node in ast.walk(defs[qual]):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name):
                    for q in by_name.get(node.func.id, ()):
                        if q not in reach:
                            work.append(q)
        for qual in sorted(reach):
            fn = defs[qual]
            for node in ast.walk(fn):
                bad = None
                if isinstance(node, ast.Call):
                    name = _impure_call(node)
                    if name:
                        bad = (f"{name}() inside jit-reachable "
                               f"{qual} — traced once, constant "
                               f"forever")
                elif isinstance(node, ast.For) and isinstance(
                        node.iter, (ast.Set, ast.SetComp)):
                    bad = (f"iteration over a set literal inside "
                           f"jit-reachable {qual} — hash order is "
                           f"not a program invariant")
                if bad is None:
                    continue
                line = node.lineno
                scope = [line, fn.lineno]
                if ctx.exempt(("impure-ok",), scope):
                    continue
                findings.append(Finding(
                    "jit-purity", ctx.rel, line,
                    bad + "; hoist it to the host caller or annotate "
                          "`# lint: impure-ok(reason)`"))
    stats["jit_roots"] = roots
    return findings


# -- 6. silent broad excepts (migrated from test_code_hygiene) ----------------

@checker("broad-except", "broad-except-ok")
def check_broad_except(repo: Repo, stats: dict):
    """``except Exception: pass`` hides index-hygiene and serving
    failures the operator needs to see — each handler must log or
    narrow the type (the reference logs every swallowed exception
    through ConcurrentLog)."""
    findings = []
    handlers = 0
    for ctx in repo.under("yacy_search_server_tpu/"):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            names = []
            if isinstance(node.type, ast.Tuple):
                names = [dotted(e) for e in node.type.elts]
            elif node.type is not None:
                names = [dotted(node.type)]
            if not any(n in ("Exception", "BaseException")
                       for n in names):
                continue
            handlers += 1
            if not (len(node.body) == 1
                    and isinstance(node.body[0], ast.Pass)):
                continue
            scope = [node.lineno, node.body[0].lineno]
            if ctx.exempt(("broad-except-ok",), scope):
                continue
            findings.append(Finding(
                "broad-except", ctx.rel, node.lineno,
                "silent `except Exception: pass` — log the failure or "
                "narrow the exception type (or annotate "
                "`# lint: broad-except-ok(reason)`)"))
    stats["broad_handlers"] = handlers
    return findings


# -- 7. kernel cost models (migrated) -----------------------------------------

ROOFLINE_REL = "yacy_search_server_tpu/ops/roofline.py"
KERNEL_SCOPES = ("yacy_search_server_tpu/ops/",
                 "yacy_search_server_tpu/ingest/")
KERNEL_FILES = ("yacy_search_server_tpu/index/devstore.py",)


def roofline_registry(repo: Repo) -> tuple[set[str], set[str]]:
    """(KERNELS keys, EXEMPT keys) read statically off ops/roofline.py
    — no jax import, same single-parse pass as everything else."""
    return (repo.dict_literal_keys(ROOFLINE_REL, "KERNELS"),
            repo.dict_literal_keys(ROOFLINE_REL, "EXEMPT"))


def kernel_contexts(repo: Repo):
    seen = set()
    for ctx in repo.under(*KERNEL_SCOPES) + \
            [c for r in KERNEL_FILES if (c := repo.get(r))]:
        if ctx.rel not in seen:
            seen.add(ctx.rel)
            yield ctx


@checker("kernel-cost-model", "costmodel-ok")
def check_kernel_cost_model(repo: Repo, stats: dict):
    """Every named device kernel (jit- or pallas-compiled) in ops/,
    ingest/ and index/devstore.py must carry a roofline cost-model
    entry — a kernel without one is invisible to the silicon
    accounting, so its perf claims cannot be stated against the
    hardware.  Exemption: `# lint: costmodel-ok(reason)` on the def
    (non-serving maintenance kernels)."""
    findings = []
    kernels, exempt = roofline_registry(repo)
    seen = []
    for ctx in kernel_contexts(repo):
        for name, fn in named_kernels(ctx):
            seen.append(name)
            if name in kernels or name in exempt:
                continue
            scope = [fn.lineno,
                     min(d.lineno for d in fn.decorator_list)
                     if fn.decorator_list else fn.lineno]
            if ctx.exempt(("costmodel-ok",), scope):
                continue
            findings.append(Finding(
                "kernel-cost-model", ctx.rel, fn.lineno,
                f"device kernel {name} has no roofline cost model — "
                f"register it in ops/roofline.KERNELS or annotate the "
                f"def `# lint: costmodel-ok(reason)`"))
    stats["kernels_seen"] = len(seen)
    stats["kernel_names"] = sorted(set(seen))
    stats["registry_kernels"] = len(kernels)
    return findings


# -- 8. serving-kernel parity oracles (migrated) ------------------------------

@checker("kernel-oracle", "oracle-ok")
def check_kernel_oracle(repo: Repo, stats: dict):
    """Serving-kernel families whose bit-identity contract rests on a
    NumPy oracle: every ``*_bp_kernel`` needs ops/packed.BP_ORACLES and
    every ``_ann_*`` kernel needs ops/ann.ANN_ORACLES (the oracle
    doubles as the host/device-loss fallback).  For these families a
    roofline EXEMPT entry is NOT acceptable — registration must be BY
    NAME.  Dead oracle entries (no kernel behind them) also flag."""
    findings = []
    kernels_reg, _exempt = roofline_registry(repo)
    bp_oracles = repo.dict_literal_keys(
        "yacy_search_server_tpu/ops/packed.py", "BP_ORACLES")
    ann_oracles = repo.dict_literal_keys(
        "yacy_search_server_tpu/ops/ann.py", "ANN_ORACLES")
    bp, annk = [], []
    dev = repo.get("yacy_search_server_tpu/index/devstore.py")
    if dev is not None:
        bp = [(n, f) for n, f in named_kernels(dev)
              if n.endswith("_bp_kernel")]
    annctx = repo.get("yacy_search_server_tpu/ops/ann.py")
    if annctx is not None:
        annk = [(n, f) for n, f in named_kernels(annctx)
                if n.startswith("_ann_")]
    for fam, found, oracles, oname in (
            ("*_bp_kernel", bp, bp_oracles, "ops/packed.BP_ORACLES"),
            ("_ann_*", annk, ann_oracles, "ops/ann.ANN_ORACLES")):
        for name, fn in found:
            ctx = dev if fam == "*_bp_kernel" else annctx
            scope = [fn.lineno,
                     min(d.lineno for d in fn.decorator_list)
                     if fn.decorator_list else fn.lineno]
            if ctx.exempt(("oracle-ok",), scope):
                continue
            if name not in oracles:
                findings.append(Finding(
                    "kernel-oracle", ctx.rel, fn.lineno,
                    f"serving kernel {name} has no NumPy oracle — "
                    f"register the parity anchor in {oname}"))
            if name not in kernels_reg:
                findings.append(Finding(
                    "kernel-oracle", ctx.rel, fn.lineno,
                    f"serving kernel {name} must be registered BY "
                    f"NAME in ops/roofline.KERNELS (an exemption is "
                    f"not acceptable for a serving kernel)"))
    # dead oracle entries: a renamed kernel must not leave one behind
    live_ann = {n for n, _ in annk}
    for dead in sorted(ann_oracles - live_ann):
        findings.append(Finding(
            "kernel-oracle", "yacy_search_server_tpu/ops/ann.py", 1,
            f"ANN_ORACLES entry {dead!r} names no live _ann_* kernel "
            f"— delete the dead oracle"))
    live_bp = {n for n, _ in bp}
    for dead in sorted(bp_oracles - live_bp):
        findings.append(Finding(
            "kernel-oracle", "yacy_search_server_tpu/ops/packed.py", 1,
            f"BP_ORACLES entry {dead!r} names no live *_bp_kernel — "
            f"delete the dead oracle"))
    stats["bp_kernels"] = sorted(live_bp)
    stats["ann_kernels"] = sorted(live_ann)
    return findings


# -- 9. wall-measuring servlets open spans (migrated) -------------------------

@checker("servlet-trace", "trace-ok")
def check_servlet_trace(repo: Repo, stats: dict):
    """Every @servlet handler that measures a wall (a t0 it later
    subtracts) or touches the roofline PROFILER must open a tracing
    span — or carry `# lint: trace-ok(reason)` on the def.  An endpoint
    that times itself outside the span spine silently drops out of the
    waterfall Performance_Trace_p renders."""
    findings = []
    handlers = 0
    for ctx in repo.under("yacy_search_server_tpu/server/servlets/"):
        for qual, fn in iter_defs(ctx.tree):
            is_servlet = any(
                isinstance(d, ast.Call) and dotted(d.func) == "servlet"
                for d in fn.decorator_list)
            if not is_servlet:
                continue
            handlers += 1
            measures = traced = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        dotted(node.value.func) in (
                            "time.time", "time.monotonic",
                            "time.perf_counter"):
                    if any(isinstance(t, ast.Name)
                           and t.id.startswith("t0")
                           for t in node.targets):
                        measures = True
                if isinstance(node, ast.Name) and node.id == "PROFILER":
                    measures = True
                if isinstance(node, ast.Call) and dotted(node.func) in (
                        "tracing.trace", "tracing.span",
                        "tracing.span_in", "tracing.begin"):
                    traced = True
            if not measures or traced:
                continue
            deco_line = min((d.lineno for d in fn.decorator_list),
                            default=fn.lineno)
            if ctx.exempt(("trace-ok",),
                          [deco_line, fn.lineno]):
                continue
            findings.append(Finding(
                "servlet-trace", ctx.rel, fn.lineno,
                f"servlet handler {fn.name} measures a wall without "
                f"opening a tracing span — wrap it in tracing.trace() "
                f"or annotate `# lint: trace-ok(reason)`"))
    stats["servlet_handlers"] = handlers
    return findings


# -- 11. tail-classifier reachability (ISSUE 15) ------------------------------

TAILATTR_REL = "yacy_search_server_tpu/utils/tailattr.py"


def tail_classifier_families(repo: Repo) -> set[str]:
    """The histogram families the tail classifier consumes or gates on,
    read statically off utils/tailattr.CLASSIFIER_FAMILIES (a
    frozenset literal whose elements may be the module's own MARKER_*
    string constants) — no import, same single-parse pass as the
    roofline registry reads."""
    ctx = repo.get(TAILATTR_REL)
    if ctx is None:
        return set()
    consts: dict[str, str] = {}
    fams: set[str] = set()
    for node in ctx.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                consts[t.id] = node.value.value
            elif t.id == "CLASSIFIER_FAMILIES" and \
                    isinstance(node.value, ast.Call) and \
                    node.value.args and \
                    isinstance(node.value.args[0], ast.Set):
                for el in node.value.args[0].elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        fams.add(el.value)
                    elif isinstance(el, ast.Name):
                        fams.add(("__name__", el.id))
    return {consts.get(f[1], "") if isinstance(f, tuple) else f
            for f in fams} - {""}


@checker("tail-reach", "tail-ok")
def check_tail_reach(repo: Repo, stats: dict):
    """Every histogram family a servlet wall observes directly
    (``histogram.observe("<family>", ...)`` anywhere under server/)
    must be reachable by the tail classifier — listed in
    utils/tailattr.CLASSIFIER_FAMILIES — or carry a reasoned
    ``# lint: tail-ok(reason)``.  A serving wall the classifier cannot
    see is a p99 bucket nothing can ever explain: it fills the SLO
    histogram but every over-threshold query it measures would
    classify blind."""
    findings = []
    fams = tail_classifier_families(repo)
    observed = 0
    for ctx in repo.under("yacy_search_server_tpu/server/"):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and dotted(node.func) == "histogram.observe"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            observed += 1
            fam = node.args[0].value
            if fam in fams:
                continue
            if ctx.exempt(("tail-ok",), [node.lineno]):
                continue
            findings.append(Finding(
                "tail-reach", ctx.rel, node.lineno,
                f"servlet wall observes histogram family {fam!r} the "
                f"tail classifier cannot reach — add it to "
                f"utils/tailattr.CLASSIFIER_FAMILIES (and teach the "
                f"classifier) or annotate `# lint: tail-ok(reason)`"))
    stats["servlet_observed_families"] = observed
    stats["classifier_families"] = len(fams)
    return findings


# -- 12. raw lock on the instrumented-lock census (ISSUE 20b) -----------------


@checker("raw-hot-lock", "rawlock-ok")
def check_raw_hot_lock(repo: Repo, stats: dict):
    """Police the lock-wait observatory's census: every
    ``file::Class::attr`` key of ``HOT_LOCK_CENSUS``
    (utils/profiling.py) must be constructed as
    ``ObservedLock``/``ObservedRLock`` in that class — a raw
    ``threading.Lock/RLock`` on a census name is a hot lock whose
    wait/hold walls silently vanish from ``yacy_lock_wait_*`` and from
    the tail classifier's lock-wait markers.  A census entry matching
    NOTHING is also a finding (the census cannot rot as code moves).
    Escape hatch: ``# lint: rawlock-ok(reason)`` on the assignment."""
    findings = []
    census: dict[str, str] = {}     # key -> rel of the census literal
    for ctx in repo.under("yacy_search_server_tpu/"):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Dict)):
                continue
            if not any(isinstance(t, ast.Name)
                       and t.id == "HOT_LOCK_CENSUS"
                       for t in node.targets):
                continue
            for k in node.value.keys:
                if isinstance(k, ast.Constant) \
                        and isinstance(k.value, str):
                    census[k.value] = ctx.rel
    observed = raw = 0
    for key, src in sorted(census.items()):
        parts = key.split("::")
        if len(parts) != 3:
            findings.append(Finding(
                "raw-hot-lock", src, 1,
                f"malformed HOT_LOCK_CENSUS key {key!r} "
                f"(want 'file::Class::attr')"))
            continue
        rel, clsname, attr = parts
        ctx = repo.get(rel)
        cls = None
        if ctx is not None:
            cls = next((n for n in ast.walk(ctx.tree)
                        if isinstance(n, ast.ClassDef)
                        and n.name == clsname), None)
        if cls is None:
            findings.append(Finding(
                "raw-hot-lock", src, 1,
                f"HOT_LOCK_CENSUS entry {key!r} matches no class — "
                f"the census rotted; update or remove the entry"))
            continue
        assigns = [n for n in ast.walk(cls)
                   if isinstance(n, ast.Assign)
                   and isinstance(n.value, ast.Call)
                   and any(_self_attr(t) == attr for t in n.targets)]
        if not assigns:
            findings.append(Finding(
                "raw-hot-lock", src, 1,
                f"HOT_LOCK_CENSUS entry {key!r} matches no "
                f"constructor assignment in {clsname} — the census "
                f"rotted; update or remove the entry"))
            continue
        for node in assigns:
            tail = dotted(node.value.func).split(".")[-1]
            if tail in ("ObservedLock", "ObservedRLock"):
                observed += 1
                continue
            if tail not in ("Lock", "RLock"):
                continue        # some other factory: not this rule's call
            if ctx.exempt(("rawlock-ok",), [node.lineno, cls.lineno]):
                continue
            raw += 1
            findings.append(Finding(
                "raw-hot-lock", ctx.rel, node.lineno,
                f"{clsname}.{attr} is on the instrumented-lock census "
                f"but is a raw threading.{tail} — use "
                f"profiling.ObservedLock/ObservedRLock so its "
                f"wait/hold walls record, or annotate "
                f"`# lint: rawlock-ok(reason)`"))
    stats["census_entries"] = len(census)
    stats["observed_locks"] = observed
    return findings
