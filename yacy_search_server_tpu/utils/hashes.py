"""Word and URL hashing — identity layer of the whole framework.

Reference behavior being reproduced (not the implementation):
- word hash: 12 base64(enhanced) chars of MD5(lowercased word)
  (reference: source/net/yacy/kelondro/data/word/Word.java:113-130)
- URL hash: 12 chars =
    [0:5]  base64(MD5(normalized url))        -- the "local" part
    [5]    hash of subdomain+port+rootpath    -- 1 char
    [6:11] host hash ("hosthash5")            -- the "global" part
    [11]   flag byte: protocol | domain-id | dom-length-key
  (reference: source/net/yacy/cora/document/id/DigestURL.java urlHashComputation)
- hosthash of a url hash = chars [6:12] (DigestURL.java:61-100)
- domain-length estimation decoded from the flag byte
  (DigestURL.java:352-375) feeding the ranking's domlength signal.

The layout is kept so DHT partition routing (horizontal by word hash,
vertical by url hash — Distribution.java) and host-grouping semantics
(hosthash prefix match) behave like the reference's network.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache
from urllib.parse import urlsplit

from .base64order import enhanced_coder

COMMON_HASH_LENGTH = 12
HOST_HASH_LENGTH = 6

_PRIVATE_PREFIX = b"_____"


@lru_cache(maxsize=100_000)
def word2hash(word: str) -> bytes:
    """12-char base64 hash of a (lowercased) word. Ring key of the RWI."""
    wordlc = word.lower()
    h = enhanced_coder.encode_substring(
        hashlib.md5(wordlc.encode("utf-8")).digest(), COMMON_HASH_LENGTH
    )
    # keep the '_____'-prefixed range reserved for private/local hashes
    while h[:5] == _PRIVATE_PREFIX:
        h = h[1:] + b"A"
    return h


def word_hashes(words: list[str]) -> list[bytes]:
    """Batch word2hash — the condense/store hot path. Uses the native C++
    MD5+base64 kernel (utils/native.py) when available; small batches stay
    on the lru-cached Python path."""
    from .native import word_hash_batch
    out = word_hash_batch(words)
    if out is not None:
        return out
    return [word2hash(w) for w in words]


def _md5_b64(s: str) -> bytes:
    return enhanced_coder.encode(hashlib.md5(s.encode("utf-8")).digest())


def hosthash5(protocol: str, host: str, port: int) -> bytes:
    """5-char host hash — the 'global' part shared by all urls of a host."""
    return _md5_b64(f"{protocol}:{host}:{port}")[:5]


def _subdom_port_path_char(subdom: str, port: int, rootpath: str) -> bytes:
    return _md5_b64(f"{subdom}:{port}:{rootpath}")[:1]


def _split_host(host: str) -> tuple[str, str]:
    """Return (subdomain, domain-without-tld)."""
    if not host or ":" in host:
        return "", ""
    p = host.rfind(".")
    dom = host[:p] if p > 0 else ""
    p = dom.rfind(".")
    if p <= 0:
        return "", dom
    return dom[:p], dom[p + 1 :]


def _split(url: str):
    """(scheme, host, port, path, query) with malformed urls tolerated —
    scraped hrefs must never crash the identity layer."""
    try:
        parts = urlsplit(url)
    except ValueError:
        # e.g. unbalanced-bracket IPv6 literal; treat as opaque path
        return "http", "", 80, "/" + url, ""
    scheme = (parts.scheme or "http").lower()
    try:
        host = (parts.hostname or "").lower()
    except ValueError:
        host = ""
    try:
        port = parts.port or default_port(scheme)
    except ValueError:
        port = default_port(scheme)
    return scheme, host, port, parts.path or "/", parts.query


def safe_host(url: str) -> str:
    """Hostname of a possibly-malformed url, lowercased; '' when absent."""
    return _split(url)[1]


def url_file_ext(url: str) -> str:
    """File extension of the url path, lowercased, capped at 8 chars;
    '' when the file name has none (CollectionSchema.url_file_ext_s /
    WebgraphSchema.target_file_ext_s normalization)."""
    path = _split(url)[3]
    name = path.rsplit("/", 1)[-1]
    return name.rsplit(".", 1)[-1].lower()[:8] if "." in name else ""


def normalform(url: str) -> str:
    scheme, host, port, path, query = _split(url)
    netloc = host if port == default_port(scheme) else f"{host}:{port}"
    q = f"?{query}" if query else ""
    return f"{scheme}://{netloc}{path}{q}"


def default_port(scheme: str) -> int:
    return {"http": 80, "https": 443, "ftp": 21, "smb": 445, "file": 0}.get(scheme, 80)


def url2hash(url: str) -> bytes:
    """12-char url hash with the reference's positional layout."""
    scheme, host, port, path, _ = _split(url)
    subdom, dom = _split_host(host)

    rootpath_start = 1 if path.startswith("/") else 0
    rootpath_end = len(path) - 2 if path.endswith("/") else len(path) - 1
    p = path.find("/", rootpath_start)
    rootpath = path[rootpath_start:p] if 0 < p < rootpath_end else ""

    l = len(dom)
    domlength_key = 0 if l <= 8 else 1 if l <= 12 else 2 if l <= 16 else 3
    is_http = scheme in ("http", "https")
    # domain-id: the reference resolves DNS to classify local/global nets
    # (Domains.getDomainID); here: 7 marks intranet-style hosts, 0 global.
    dom_id = 7 if (not dom or host in ("localhost", "127.0.0.1")) else 0
    flagbyte = (0 if is_http else 32) | (dom_id << 2) | domlength_key

    h = bytearray()
    h += _md5_b64(normalform(url))[:5]
    h += _subdom_port_path_char(subdom, port, rootpath)
    h += hosthash5(scheme, host, port)
    h += enhanced_coder.encode_long(flagbyte, 1)
    assert len(h) == COMMON_HASH_LENGTH
    return bytes(h)


def hosthash(urlhash: bytes) -> bytes:
    """6-char host hash part of a url hash (positions 6..12)."""
    return urlhash[6:12]


def url_comps(url: str) -> int:
    """Number of url path/host components — the single source for the
    `urlcomps` ranking signal (postings column and metadata column must
    agree, or the same doc scores differently per read path)."""
    return min(len([c for c in url.split("/") if c]), 255)


def dom_length_estimation(urlhash: bytes) -> int:
    """Estimated domain length from the url-hash flag byte."""
    flagbyte = enhanced_coder.decode_byte(urlhash[11])
    return {0: 4, 1: 10, 2: 14, 3: 20}.get(flagbyte & 3, 20)


def dom_length_normalized(urlhash: bytes) -> int:
    # NB: reproduces the reference expression `domLengthEstimation(h) << 8 / 20`
    # which Java parses as `est << (8 / 20)` == est << 0 == est.
    return dom_length_estimation(urlhash)


def is_local_urlhash(urlhash: bytes) -> bool:
    flagbyte = enhanced_coder.decode_byte(urlhash[11])
    return ((flagbyte >> 2) & 7) == 7


def host_dnc(host: str) -> tuple[str, str]:
    """(dnc, organizationdnc): the reversed "domain name core" pair
    (reference Domains.getDNC — "www.example.com" -> dnc "com.example",
    organizationdnc "com.example.www"). Dotless hosts ("localhost") have
    no core: both come back empty."""
    if not host or "." not in host:
        return "", ""
    _sub, org = _split_host(host)
    tld = host.rsplit(".", 1)[-1]
    dnc = ".".join(reversed([p for p in (org, tld) if p]))
    return dnc, ".".join(reversed(host.split(".")))
