"""Two-layer key-value configuration + network-unit definitions.

Reproduces the reference's config model (reference:
source/net/yacy/server/serverSwitch.java:273-334,453): an immutable defaults
layer overlaid by a mutable settings file that is persisted on every change,
plus separate *network unit* definitions that rewire DHT/crawl behavior
(reference: defaults/yacy.network.freeworld.unit selected by
`network.unit.definition`).
"""

from __future__ import annotations

import os
import threading
from typing import Iterator


def _parse_kv(text: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if "=" not in line:
            continue
        k, v = line.split("=", 1)
        out[k.strip()] = v.strip()
    return out


class Config:
    """defaults (read-only) overlaid by settings (mutable, persisted)."""

    def __init__(self, defaults: dict[str, str] | None = None,
                 settings_path: str | None = None):
        self._defaults: dict[str, str] = dict(defaults or {})
        self._settings: dict[str, str] = {}
        self._path = settings_path
        self._lock = threading.RLock()
        if settings_path and os.path.exists(settings_path):
            with open(settings_path, "r", encoding="utf-8") as f:
                self._settings = _parse_kv(f.read())
        # env override layer (ISSUE 19): a spawned child (mesh member,
        # chaos harness) has no wire yet when its Switchboard builds, so
        # knobs the engines read once at construction — incident
        # cooldowns, admission burst, conviction windows — are injected
        # at spawn: YACY_CONFIG_OVERRIDES="k1=v1,k2=v2" wins over the
        # settings file (and persists with it if the node later set()s)
        env = os.environ.get("YACY_CONFIG_OVERRIDES", "")
        for part in env.split(","):
            part = part.strip()
            if part and "=" in part:
                k, _, v = part.partition("=")
                self._settings[k.strip()] = v.strip()

    @classmethod
    def from_files(cls, defaults_path: str, settings_path: str | None = None) -> "Config":
        with open(defaults_path, "r", encoding="utf-8") as f:
            defaults = _parse_kv(f.read())
        return cls(defaults, settings_path)

    # -- reads ---------------------------------------------------------------

    def get(self, key: str, default: str = "") -> str:
        with self._lock:
            if key in self._settings:
                return self._settings[key]
            return self._defaults.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        try:
            return int(self.get(key, str(default)))
        except ValueError:
            return default

    def get_float(self, key: str, default: float = 0.0) -> float:
        try:
            return float(self.get(key, str(default)))
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, "true" if default else "false").lower()
        return v in ("true", "1", "yes", "on")

    def keys(self) -> Iterator[str]:
        with self._lock:
            return iter(sorted(set(self._defaults) | set(self._settings)))

    # -- writes --------------------------------------------------------------

    def set(self, key: str, value) -> None:
        if isinstance(value, bool):
            value = "true" if value else "false"
        with self._lock:
            self._settings[key] = str(value)
            self._persist()

    def _persist(self) -> None:
        if not self._path:
            return
        tmp = self._path + ".tmp"
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            for k in sorted(self._settings):
                f.write(f"{k}={self._settings[k]}\n")
        os.replace(tmp, self._path)


# Default network unit, mirroring the operational constants of the
# reference's freeworld unit (defaults/yacy.network.freeworld.unit):
# 2^4 = 16 vertical partitions, redundancy junior=1/senior=3,
# 3000 ms / 10 results remote-search budget.
FREEWORLD_UNIT: dict[str, str] = {
    "network.unit.name": "freeworld",
    "network.unit.description": "Public YaCy-equivalent network",
    "network.unit.dht": "true",
    "network.unit.dht.partitionExponent": "4",
    "network.unit.dhtredundancy.junior": "1",
    "network.unit.dhtredundancy.senior": "3",
    "network.unit.remotesearch.maxcount": "10",
    "network.unit.remotesearch.maxtime": "3000",
    "network.unit.remotecrawl.speed": "60",
}

INTRANET_UNIT: dict[str, str] = {
    "network.unit.name": "intranet",
    "network.unit.description": "Closed intranet network",
    "network.unit.dht": "false",
    "network.unit.dht.partitionExponent": "0",
    "network.unit.dhtredundancy.junior": "1",
    "network.unit.dhtredundancy.senior": "1",
    "network.unit.remotesearch.maxcount": "100",
    "network.unit.remotesearch.maxtime": "3000",
    "network.unit.remotecrawl.speed": "0",
}

NETWORK_UNITS = {"freeworld": FREEWORLD_UNIT, "intranet": INTRANET_UNIT}


class NetworkUnit:
    """Selected network definition; switching rewires DHT + crawl behavior."""

    def __init__(self, name: str = "freeworld", overrides: dict[str, str] | None = None):
        base = dict(NETWORK_UNITS.get(name, FREEWORLD_UNIT))
        if overrides:
            base.update(overrides)
        self.props = base
        self.name = base["network.unit.name"]
        self.dht_enabled = base.get("network.unit.dht", "false") == "true"
        self.partition_exponent = int(base.get("network.unit.dht.partitionExponent", "0"))
        self.redundancy_junior = int(base.get("network.unit.dhtredundancy.junior", "1"))
        self.redundancy_senior = int(base.get("network.unit.dhtredundancy.senior", "1"))
        self.remotesearch_maxcount = int(base.get("network.unit.remotesearch.maxcount", "10"))
        self.remotesearch_maxtime_ms = int(base.get("network.unit.remotesearch.maxtime", "3000"))
        self.remotecrawl_speed_ppm = int(base.get("network.unit.remotecrawl.speed", "0"))
