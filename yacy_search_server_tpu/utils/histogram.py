"""Windowed log-bucket latency histograms — the node's percentile spine.

Before this module every surface that wanted a p50/p95 recomputed it
from raw samples at read time: `Performance_Trace_p` iterated the whole
trace ring per page load, the devstore kept 20k-entry deques, and
`/metrics` exposed no distribution at all — a Prometheus scraper saw
counters and gauges but could never ask "how slow is slow".  This module
gives every hot wall ONE cheap recording surface (ISSUE 4 tentpole):

- **HDR-style fixed buckets.** Log-linear: octaves of 2 from 2^-5 ms to
  2^20 ms, each split into 4 linear sub-buckets (≤ 25 % bucket width, so
  an interpolated percentile is within ~12.5 % of the true sample — the
  agreement bound BASELINE.md pins against the raw-sample percentiles).
  Bucket index is a `math.frexp` + two integer ops: zero alloc.
- **Windowed ring rotation.** Counts land in the current of `WINDOWS`
  ring slots; the slot advances every `ROTATE_EVERY_S` (lazily on
  record, or from the health tick), so `percentile()` answers from the
  last ~WINDOWS×ROTATE_EVERY_S minutes, not process lifetime.  Separate
  CUMULATIVE counts back the Prometheus `_bucket/_sum/_count` series,
  which must be monotonic by contract.
- **Trace-id exemplars.** A recording at or above the window p95 (cached
  at rotation, so the check is one compare) stamps its trace id on its
  bucket — `/metrics` exposes it OpenMetrics-style and
  `Performance_Health_p`/`Performance_Trace_p` link the slow bucket
  straight to the waterfall.
- **Mergeable.** Fixed shared bounds mean bucket-count vectors add;
  `merge_counts` + `percentile_from_counts` serve cross-store and
  cross-window aggregation.

`pctl` here is THE nearest-rank percentile convention — tracing,
profiler, devstore and bench all delegate to it (one implementation,
satellite of ISSUE 4).
"""

from __future__ import annotations

import math
import threading
import time
from collections import OrderedDict

# window geometry: 6 slots × 30 s = percentiles over the last ~3 minutes
WINDOWS = 6
ROTATE_EVERY_S = 30.0

# log-linear bucket grid: octaves [2^e, 2^(e+1)) ms for e in
# [_EXP_LO, _EXP_HI), each split into _SUBS linear sub-buckets
_EXP_LO = -5                 # 2^-5 ms = 31.25 µs
_EXP_HI = 20                 # 2^20 ms ≈ 17.5 min; above → +Inf bucket
_SUBS = 4
N_BUCKETS = (_EXP_HI - _EXP_LO) * _SUBS + 1      # +1: the +Inf bucket

# upper bound (`le`) of every finite bucket, in ms
BUCKET_BOUNDS_MS: tuple = tuple(
    (1.0 + (s + 1) / _SUBS) * (2.0 ** e)
    for e in range(_EXP_LO, _EXP_HI) for s in range(_SUBS))


def bucket_index(ms: float) -> int:
    """Bucket for a value (clamped into [0, N_BUCKETS-1]); ~4 float ops.
    Bounds are INCLUSIVE upper edges (`le` semantics, the Prometheus
    contract): a value exactly on a bound lands in the bucket whose
    `le` it equals."""
    if ms <= 0.0:
        return 0
    frac, exp = math.frexp(ms)          # ms = frac * 2^exp, frac ∈ [0.5, 1)
    idx = (exp - 1 - _EXP_LO) * _SUBS + int((frac - 0.5) * (2 * _SUBS))
    if idx <= 0:
        return 0
    if idx >= N_BUCKETS - 1:
        return N_BUCKETS - 1
    # frexp treats a bound as the exclusive low edge of the NEXT bucket;
    # pull exact-boundary values back into their `le` bucket
    if ms <= BUCKET_BOUNDS_MS[idx - 1]:
        idx -= 1
    return idx


def pctl(sorted_values: list, q: float) -> float:
    """Nearest-rank percentile over a SORTED list — the one convention
    shared by tracing, the profiler, the batcher counters and bench."""
    if not sorted_values:
        return 0.0
    return sorted_values[min(len(sorted_values) - 1,
                             int(len(sorted_values) * q))]


def percentile_from_counts(counts, q: float) -> float:
    """Percentile from a bucket-count vector (windowed or merged), with
    linear interpolation inside the straddling bucket.  The +Inf bucket
    answers with the largest finite bound (a floor, never an invention)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = min(total - 1, int(total * q))
    cum = 0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c > rank:
            if i >= N_BUCKETS - 1:
                return BUCKET_BOUNDS_MS[-1]
            lo = BUCKET_BOUNDS_MS[i - 1] if i > 0 else 0.0
            hi = BUCKET_BOUNDS_MS[i]
            return lo + (hi - lo) * ((rank - cum) + 0.5) / c
        cum += c
    return BUCKET_BOUNDS_MS[-1]


def merge_counts(count_vectors) -> list:
    """Sum bucket-count vectors (all histograms share one bound grid, so
    counts are mergeable by construction)."""
    out = [0] * N_BUCKETS
    for vec in count_vectors:
        for i, c in enumerate(vec):
            out[i] += c
    return out


def fraction_over_counts(counts, threshold_ms: float) -> float:
    """Fraction of a bucket-count vector above `threshold_ms` (the
    straddling bucket contributes linearly) — the burn-rate numerator,
    shared by the per-node SLO rule (via Histogram.fraction_over) and
    the fleet-level rule over MERGED peer digests (utils/fleet.py)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    ti = bucket_index(threshold_ms)
    bad = float(sum(counts[ti + 1:]))
    lo = BUCKET_BOUNDS_MS[ti - 1] if ti > 0 else 0.0
    hi = BUCKET_BOUNDS_MS[ti] if ti < N_BUCKETS - 1 \
        else BUCKET_BOUNDS_MS[-1]
    if hi > lo:
        bad += counts[ti] * max(0.0, min(1.0, (hi - threshold_ms)
                                         / (hi - lo)))
    return bad / total


def counts_to_sparse(counts) -> dict:
    """Bucket-count vector -> the digest wire form `{"i": [...], "c":
    [...]}` (indices + counts of the non-empty buckets only).  Lossless:
    `counts_from_sparse` reconstructs the exact vector, so merged
    mesh-wide percentiles equal the ones computed from the raw vectors
    (the ISSUE 5 acceptance property)."""
    idx: list[int] = []
    cts: list[int] = []
    for i, c in enumerate(counts):
        if c:
            idx.append(i)
            cts.append(int(c))
    return {"i": idx, "c": cts}


def counts_from_sparse(obj) -> list | None:
    """Tolerant decode of the digest wire form; None on malformed input
    (the caller drops the family, never the whole digest).  Indices
    outside this build's grid — a future version with more buckets —
    clamp into the edge buckets instead of failing the merge."""
    if not isinstance(obj, dict):
        return None
    idx, cts = obj.get("i"), obj.get("c")
    if not isinstance(idx, (list, tuple)) or \
            not isinstance(cts, (list, tuple)) or len(idx) != len(cts):
        return None
    out = [0] * N_BUCKETS
    try:
        for i, c in zip(idx, cts):
            i, c = int(i), int(c)
            if c < 0:
                return None
            out[min(max(i, 0), N_BUCKETS - 1)] += c
    except (TypeError, ValueError):
        return None
    return out


class Histogram:
    """One latency family: cumulative counts (Prometheus) + a windowed
    ring (operator percentiles) + per-bucket trace-id exemplars."""

    __slots__ = ("name", "help", "_lock", "counts", "sum_ms", "count",
                 "_win", "_wi", "_next_rot", "_p95_cache", "exemplars")

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_ or f"latency of {name} in ms"
        self._lock = threading.Lock()
        self.counts = [0] * N_BUCKETS          # cumulative (monotonic)
        self.sum_ms = 0.0
        self.count = 0
        self._win = [[0] * N_BUCKETS for _ in range(WINDOWS)]
        self._wi = 0
        self._next_rot = time.monotonic() + ROTATE_EVERY_S
        self._p95_cache = 0.0                  # refreshed at rotation
        # bucket -> (trace_id, value_ms, unix_ts); only values at/above
        # the cached window p95 claim a slot (slow buckets link to traces)
        self.exemplars: list = [None] * N_BUCKETS

    # -- recording -----------------------------------------------------------

    def record(self, ms: float, trace_id: str | None = None) -> None:
        idx = bucket_index(ms)
        now = time.monotonic()
        with self._lock:
            if now >= self._next_rot:
                self._rotate_locked(now)
            self.counts[idx] += 1
            self.sum_ms += ms
            self.count += 1
            self._win[self._wi][idx] += 1
            if trace_id is not None and (
                    ms >= self._p95_cache or self.exemplars[idx] is None):
                self.exemplars[idx] = (trace_id, ms, time.time())

    def _rotate_locked(self, now: float) -> None:
        # cache p95 BEFORE clearing the next slot: the exemplar gate
        # compares against the window that just closed
        self._p95_cache = percentile_from_counts(
            merge_counts(self._win), 0.95)
        steps = 1 + min(WINDOWS - 1,
                        int((now - self._next_rot) / ROTATE_EVERY_S))
        for _ in range(steps):
            self._wi = (self._wi + 1) % WINDOWS
            self._win[self._wi] = [0] * N_BUCKETS
        self._next_rot = now + ROTATE_EVERY_S
        # exemplars age out at the window horizon: a bucket must never
        # keep pointing at a trace from hours ago (likely evicted from
        # the bounded trace ring by then)
        cut = time.time() - WINDOWS * ROTATE_EVERY_S
        self.exemplars = [e if e is not None and e[2] >= cut else None
                          for e in self.exemplars]

    def rotate(self) -> None:
        """Force a window advance (the health tick's rotation driver)."""
        with self._lock:
            self._rotate_locked(time.monotonic())

    def reset_window(self) -> None:
        """Drop every retained window sample and the cached p95 (the
        cumulative Prometheus counters stay monotonic).  A harness
        calls this at the warmup/measurement boundary: compile-era
        walls would otherwise sit in the merged ring for
        WINDOWS*ROTATE_EVERY_S and hold the exemplar gate far above
        the live workload."""
        with self._lock:
            self._win = [[0] * N_BUCKETS for _ in range(WINDOWS)]
            self._wi = 0
            self._p95_cache = 0.0
            self._next_rot = time.monotonic() + ROTATE_EVERY_S

    # -- reading -------------------------------------------------------------

    @property
    def p95_cache(self) -> float:
        """The window p95 cached at the last rotation — the exemplar
        election threshold, reused by the tail-attribution gate
        (ISSUE 15): a value at/above it is exemplar-worthy, so it gets
        classified.  Unlocked read of an atomically-replaced float (the
        same discipline record() uses for its compare)."""
        # lint: unlocked-ok(float replaced atomically under _lock at
        # rotation; a stale read only shifts one gating decision by a
        # rotation interval)
        return self._p95_cache

    def windowed_counts(self, last: int | None = None) -> list:
        """Merged bucket counts over the newest `last` windows (default:
        all retained)."""
        with self._lock:
            k = WINDOWS if last is None else max(1, min(last, WINDOWS))
            vecs = [self._win[(self._wi - i) % WINDOWS] for i in range(k)]
            return merge_counts(vecs)

    def percentile(self, q: float, last: int | None = None) -> float:
        """Windowed percentile (the last ~N minutes, not process life)."""
        return percentile_from_counts(self.windowed_counts(last), q)

    def windowed_count(self, last: int | None = None) -> int:
        return sum(self.windowed_counts(last))

    def window_seconds(self, last: int | None = None) -> float:
        """Wall time the newest `last` windows actually cover: the
        CURRENT slot counts only its elapsed fill (a rate computed over
        the full ROTATE_EVERY_S right after a rotation would
        under-state qps and flap threshold gates)."""
        k = WINDOWS if last is None else max(1, min(last, WINDOWS))
        with self._lock:
            elapsed = ROTATE_EVERY_S - max(
                0.0, self._next_rot - time.monotonic())
        return max(1.0, min(elapsed, ROTATE_EVERY_S)) \
            + (k - 1) * ROTATE_EVERY_S

    def fraction_over(self, threshold_ms: float,
                      last: int | None = None) -> tuple[float, int]:
        """(fraction of windowed observations above `threshold_ms`,
        windowed total) — the burn-rate numerator for SLO rules.  The
        straddling bucket contributes linearly."""
        counts = self.windowed_counts(last)
        total = sum(counts)
        if total <= 0:
            return 0.0, 0
        return fraction_over_counts(counts, threshold_ms), total

    def snapshot(self) -> dict:
        """Cumulative view for the Prometheus exposition."""
        with self._lock:
            return {"counts": list(self.counts), "sum_ms": self.sum_ms,
                    "count": self.count,
                    "exemplars": list(self.exemplars)}


# -- registry ----------------------------------------------------------------

_reg_lock = threading.Lock()
_REG: "OrderedDict[str, Histogram]" = OrderedDict()
_enabled = True


def set_enabled(on: bool) -> None:
    """Global record gate (the bench --health-overhead A/B switch)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def histogram(name: str, help_: str = "") -> Histogram:
    """Get-or-create; families are created once and live forever (the
    exposition iterates this registry, so every histogram registered is
    exported by construction — hygiene-tested)."""
    h = _REG.get(name)
    if h is None:
        with _reg_lock:
            h = _REG.get(name)
            if h is None:
                h = _REG[name] = Histogram(name, help_)
    return h


def observe(name: str, ms: float, trace_id: str | None = None) -> None:
    """Record one wall into the named family (the one call every
    instrumented site makes)."""
    if not _enabled:
        return
    histogram(name).record(ms, trace_id)


def get(name: str) -> Histogram | None:
    return _REG.get(name)


def all_histograms() -> list:
    with _reg_lock:
        return list(_REG.values())


def rotate_all() -> None:
    for h in all_histograms():
        h.rotate()


def reset_windows() -> None:
    """Drop the windowed samples of every family (cumulative counters
    untouched) — the warmup/measurement boundary reset."""
    for h in all_histograms():
        h.reset_window()


def rotate_due() -> None:
    """Advance the window ring of every histogram whose rotation
    deadline has passed — the health tick's rotation driver.  Recording
    rotates lazily, but an IDLE family would otherwise freeze its last
    windows forever (a sticky SLO verdict after traffic stops)."""
    now = time.monotonic()
    for h in all_histograms():
        with h._lock:
            if now >= h._next_rot:
                h._rotate_locked(now)


def reset() -> None:
    """Drop every family's data (tests/bench isolation).  The canonical
    families are re-registered empty: health rules and the exposition
    reference them unconditionally."""
    with _reg_lock:
        _REG.clear()
    for _n, _h in CANONICAL.items():
        histogram(_n, _h)


def prom_name(name: str) -> str:
    """`servlet.serving` -> `yacy_servlet_serving_ms` (the exposition
    family name)."""
    safe = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return f"yacy_{safe}_ms"


# span names that wrap (nearly) the whole request: real walls, but never
# a *stage* verdict — excluded from tail dominance in stage_table
WRAPPER_FAMILIES = frozenset({"switchboard.search", "servlet.serving"})
# trace-root / segment-root families (they cover their children)
ROOT_PREFIXES = ("servlet.", "peer.", "pipeline.")
# background-workload families (crawl fetches, DHT shipping, per-doc
# indexing): real walls, but they must never decide a SERVING latency
# verdict — the trace-ring summary they replace only ever saw serving
# traces, and a multi-second crawl fetch would otherwise headline the
# Performance_Trace_p stage table of a node that merely crawls
BACKGROUND_PREFIXES = ("index.", "pipeline.", "crawler.", "crawl.",
                       "dht.", "ingest.")


def stage_table(exclude_prefixes: tuple = BACKGROUND_PREFIXES) -> dict:
    """Per-family windowed count/p50/p95 plus the tail-dominant stage —
    the `Performance_Trace_p` summary, now answered from the windowed
    histograms instead of re-walking the trace ring per page load
    (ISSUE 4 satellite).  `exclude_prefixes` drops whole workload
    classes from the table (default: the per-document indexing stages,
    whose walls would skew a search-latency verdict)."""
    out = {}
    for h in all_histograms():
        if any(h.name.startswith(p) for p in exclude_prefixes):
            continue
        counts = h.windowed_counts()
        n = sum(counts)
        if n == 0:
            continue
        out[h.name] = {
            "count": n,
            "p50_ms": round(percentile_from_counts(counts, 0.50), 3),
            "p95_ms": round(percentile_from_counts(counts, 0.95), 3)}
    inner = {k: v for k, v in out.items()
             if k not in WRAPPER_FAMILIES
             and not k.startswith(ROOT_PREFIXES)}
    tail = max(inner, key=lambda k: inner[k]["p95_ms"]) if inner else ""
    return {"stages": out, "tail_dominant_stage": tail}


# canonical families (pre-registered so health rules and the exposition
# never reference a family that does not exist yet — hygiene-tested):
# every hot wall ISSUE 4 names records into one of these
CANONICAL = {
    "servlet.serving": "full servlet dispatch+render wall per request",
    "devstore.batch": "device batcher enqueue→dispatch→result wall",
    "mesh.batch": "mesh batcher enqueue→dispatch→result wall",
    "mesh.collective": "mesh SPMD collective program wall per dispatch",
    "kernel.issue": "host-side async kernel issue wall",
    "kernel.device": "in-flight device-execution window",
    "kernel.fetch": "blocking device→host result fetch wall",
    "crawler.fetch": "crawler document fetch wall",
    "dht.transfer": "DHT index-transfer RPC wall",
    "index.parsedocument": "indexing pipeline stage 1 wall",
    "index.condensedocument": "indexing pipeline stage 2 wall",
    "index.webstructureanalysis": "indexing pipeline stage 3 wall",
    "index.storedocumentindex": "indexing pipeline stage 4 wall",
    # crawl-to-searchable SLO (ISSUE 13a, ingest/slo.py — its FAMILIES
    # dict mirrors these entries and a hygiene test pins the mirror):
    # write-path latency tiers + the bounded-buffer backpressure wall.
    # "ingest." is a BACKGROUND prefix: freshness walls must never
    # decide a SERVING latency verdict
    "ingest.searchable": "crawl-to-searchable: pipeline entry -> doc "
                         "servable from the RWI RAM buffer",
    "ingest.flushed": "pipeline entry -> RWI flush covering the doc "
                      "returned (immutable/durable run)",
    "ingest.device": "pipeline entry -> run bit-packed onto the device "
                     "tier (serves from placed blocks)",
    "ingest.backpressure": "writer wall blocked in the bounded RWI RAM "
                           "buffer (counted backpressure)",
    # lock-wait observatory (ISSUE 20b, utils/profiling.py): wait+hold
    # walls per instrumented hot lock — one wait/hold pair per entry of
    # profiling.HOT_LOCK_CENSUS (a hygiene test pins the mirror), so
    # the yacy_lock_wait_*/yacy_lock_hold_* series zero-fill before any
    # contention ever happens
    "lock.wait.devstore": "acquisition wait on the devstore store lock",
    "lock.hold.devstore": "hold wall on the devstore store lock",
    "lock.wait.devstore_tune": "acquisition wait on the batcher tune lock",
    "lock.hold.devstore_tune": "hold wall on the batcher tune lock",
    "lock.wait.rwi": "acquisition wait on the RWI store lock",
    "lock.hold.rwi": "hold wall on the RWI store lock",
    "lock.wait.dense_fwd": "acquisition wait on the dense forward-block "
                           "upload lock",
    "lock.hold.dense_fwd": "hold wall on the dense forward-block "
                           "upload lock",
    "lock.wait.mesh_plock": "acquisition wait on the mesh member's "
                            "pending-step lock",
    "lock.hold.mesh_plock": "hold wall on the mesh member's "
                            "pending-step lock",
    "lock.wait.search_cache": "acquisition wait on the search-event "
                              "cache lock",
    "lock.hold.search_cache": "hold wall on the search-event cache lock",
}

for _name, _help in CANONICAL.items():
    histogram(_name, _help)
