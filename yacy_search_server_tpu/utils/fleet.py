"""Coordinator-free fleet observability — gossiped metric digests.

The paper's single load-bearing architectural fact is that YaCy has NO
central coordinator: there is no scrape target list, no federation
server, no node that "owns" the fleet view.  Every observability layer
built so far (roofline accounting, the trace spine, the health engine)
is strictly node-local — a node can tell *itself* it is sick, but no
node can see the mesh.  This module closes that gap the P2P way
(ISSUE 5 tentpole):

- **Metric digest.** Each node periodically renders a compact (<2 KiB)
  JSON table: sparse windowed bucket-count vectors for the key
  histogram families (`DIGEST_FAMILIES`), its health-rule states, cache
  hit counters, batcher queue depths, the arena epoch and a digest
  sequence number.  Every field maps to a series on the node's OWN
  `/metrics` exposition (`digest_series` — the no-dead-digest-fields
  hygiene gate), so a digest is exactly a compressed remote scrape.
- **Piggyback gossip.** Digests ride the wire exchanges the DHT already
  pays for: `peers/protocol.Protocol._call` attaches the digest to
  outgoing RPCs (hello pings, remote searches, transferRWI chunks) at a
  per-peer rate limit, and `peers/server.PeerServer.handle` answers a
  digest-bearing caller with its own — no new RPC, no scrape loop.
  `peers/javawire.py` carries the same digest as an `xdigest` multipart
  part on the Java wire.
- **Mergeable mesh percentiles.** Because every histogram shares ONE
  fixed bucket grid (`histogram.merge_counts` is lossless integer
  addition by construction), any node can compute mesh-wide p50/p95/p99
  by merging its peers' digest vectors with its own windowed counts —
  every node converges on the same (eventually consistent) fleet view
  without a coordinator, the way Prometheus federation does WITH one.
- **Staleness semantics.** Received digests are kept per peer (keyed by
  seed hash) and evicted after `fleet.staleS` seconds without a fresh
  one; per-peer sequence numbers drop replayed/reordered digests.  A
  stale peer simply leaves the merged view — absence, not zeros.

Version-skew tolerance is a wire contract (ISSUE 5 satellite): unknown
digest fields are ignored, missing histogram families merge as ABSENT
(never as zero-filled vectors), and malformed families are dropped
individually without rejecting the rest of the digest.
"""

from __future__ import annotations

import json
import threading
import time

from . import histogram, profiling, tailattr

# payload key carrying the digest on every in-band transport (the
# fleet-table analogue of tracing.PAYLOAD_KEY); the Java wire carries it
# as the `xdigest` multipart part (peers/javawire.DIGEST_PART)
PAYLOAD_KEY = "_digest"

DIGEST_VERSION = 1

# the histogram families a digest ships: the serving tail (the SLO
# surface), the device-execution window (the silicon surface) and the
# DHT transfer wall (the P2P surface)
DIGEST_FAMILIES = ("servlet.serving", "kernel.device", "dht.transfer")

DEFAULT_BYTE_BUDGET = 2048          # the <2 KiB wire budget (bench-pinned)
DEFAULT_STALE_S = 300.0
DEFAULT_SEND_INTERVAL_S = 10.0
DEFAULT_RENDER_TTL_S = 2.0
MAX_TS_SKEW_S = 600.0               # inbound ts clamp (anti-lockout)

STATE_NAMES = ("ok", "warn", "critical")


def peer_key(peer_hash) -> str:
    """THE canonical str form of a peer hash.  Seed hashes are bytes,
    digest/table keys are str; avoid-set membership, blackhole lookups
    and RTT notes all compare through this one normalization — a
    second hand-rolled copy drifting (different errors= mode, raw
    str()) would silently break peer matching across the avoidance
    path."""
    return peer_hash.decode("ascii", "replace") \
        if isinstance(peer_hash, bytes) else str(peer_hash)


def encode_digest(digest: dict) -> str:
    """Compact JSON — the one wire encoding all three transports share
    (the JSON transports embed the dict itself; the Java wire carries
    this string as a part)."""
    return json.dumps(digest, separators=(",", ":"), sort_keys=True)


def digest_bytes(digest: dict) -> int:
    return len(encode_digest(digest))


def decode_act_cause(act: dict) -> str:
    """Tolerant decode of the digest's cause index back to its canon
    label; out-of-range/absent (version skew) reads as unattributed."""
    try:
        i = int(act.get("c", -1))
    except (TypeError, ValueError):
        i = -1
    return tailattr.CAUSES[i] if 0 <= i < len(tailattr.CAUSES) \
        else "unattributed"


def digest_series(digest: dict) -> dict:
    """Map every field a digest emits to the `/metrics` sample key it
    summarizes.  THE hygiene contract (ISSUE 5 satellite, mirroring the
    no-dead-rules gate): a digest field that resolves to no series on
    the local exposition is dead weight on every wire exchange."""
    out: dict[str, str] = {}
    for fam in digest.get("hist", {}):
        out[f"hist.{fam}"] = histogram.prom_name(fam) + "_count"
    for rule in digest.get("rules", {}):
        out[f"rules.{rule}"] = f'yacy_health_rule{{rule="{rule}"}}'
    if "health" in digest:
        out["health"] = "yacy_health_status"
    if "cache" in digest:
        out["cache.hits"] = \
            'yacy_device_serving_total{counter="rank_cache_hits"}'
        out["cache.served"] = \
            'yacy_device_serving_total{counter="queries_served"}'
    if "queues" in digest:
        out["queues.incoming"] = 'yacy_batcher_queue_depth{queue="incoming"}'
        out["queues.inflight"] = 'yacy_batcher_queue_depth{queue="inflight"}'
    if "epoch" in digest:
        out["epoch"] = "yacy_device_arena_epoch"
    if "proc" in digest:
        # multi-process mesh identity (ISSUE 12); zero-filled defaults
        # on single-process nodes so the series resolve everywhere
        out["proc.pid"] = 'yacy_mesh_process{field="pid"}'
        out["proc.id"] = 'yacy_mesh_process{field="process_id"}'
        out["proc.n"] = 'yacy_mesh_process{field="num_processes"}'
        out["proc.lost"] = "yacy_device_lost"
    if "act" in digest:
        # per-member serving rung + tail-cause top-1 (ISSUE 15
        # satellite): a degraded member is visible in Network_Health_p
        # BEFORE it becomes a straggler verdict.  The cause travels as
        # an index into the zero-filled tailattr.CAUSES canon, so its
        # labeled series resolves on every node's exposition.
        out["act.l"] = "yacy_degrade_level"
        out["act.c"] = ('yacy_tail_cause_total{cause="'
                        + decode_act_cause(digest["act"]) + '"}')
        if "p" in digest["act"]:
            # whitebox top-role index (ISSUE 20d): resolves against the
            # zero-filled per-role sample counters; version skew (an
            # old digest without the field) simply omits the mapping
            out["act.p"] = (
                'yacy_prof_role_samples_total{role="'
                + profiling.decode_role(digest["act"].get("p")) + '"}')
    if "tiers" in digest:
        # compact tier occupancy (ISSUE 8): KiB per residency tier +
        # total promotions — the mesh view of who is paging
        out["tiers.h"] = 'yacy_device_hbm_bytes{tier="hot"}'
        out["tiers.w"] = 'yacy_device_hbm_bytes{tier="warm"}'
        out["tiers.c"] = 'yacy_device_hbm_bytes{tier="cold"}'
        out["tiers.p"] = \
            'yacy_tier_promotions_total{src="warm",dst="hot"}'
        out["tiers.d"] = 'yacy_device_hbm_bytes{tier="dense"}'
        out["tiers.ah"] = 'yacy_device_hbm_bytes{tier="ann_hot"}'
        out["tiers.aw"] = 'yacy_device_hbm_bytes{tier="ann_warm"}'
        out["tiers.ac"] = 'yacy_device_hbm_bytes{tier="ann_cold"}'
    return out


class FleetTable:
    """One node's fleet view: its own digest renderer plus the per-peer
    store of received digests.  Constructed on every Switchboard (cheap:
    no threads, no I/O); the peer stack wires itself in via
    `peers/node.P2PNode` (sets `my_hash`, hands the table to the
    Protocol client)."""

    def __init__(self, sb):
        cfg = sb.config
        self.sb = sb
        self.my_hash = ""               # set by P2PNode (seed hash str)
        self.enabled = cfg.get_bool("fleet.enabled", True)
        self.stale_s = cfg.get_float("fleet.staleS", DEFAULT_STALE_S)
        self.send_interval_s = cfg.get_float(
            "fleet.sendIntervalS", DEFAULT_SEND_INTERVAL_S)
        self.render_ttl_s = cfg.get_float(
            "fleet.renderTtlS", DEFAULT_RENDER_TTL_S)
        self.byte_budget = cfg.get_int(
            "fleet.byteBudget", DEFAULT_BYTE_BUDGET)
        self._lock = threading.Lock()
        # peer hash -> sanitized digest entry (decoded hist vectors,
        # receive timestamps, wire size)
        self._peers: dict[str, dict] = {}
        self._sent: dict[str, float] = {}       # peer hash -> last attach
        # peer hash -> (last RPC wall ms, noted-at monotonic)
        self._rtt_ms: dict[str, tuple[float, float]] = {}
        self._seq = 0
        self._last_evict = -1e9
        self._cached: dict | None = None
        self._cached_mono = -1e9
        self.last_digest_bytes = 0
        self.rendered_count = 0
        self.received_count = 0
        self.ignored_count = 0
        # test seam: per-node local count vectors.  Histograms are
        # process-global, so N co-hosted loopback nodes would otherwise
        # all digest the SAME vectors; production single-node processes
        # never set this.
        self._local_counts_fn = None
        # remote-search actuation counters (ISSUE 9): every skip /
        # adaptive-timeout decision the fleet view drives must be
        # attributable — exported as yacy_remotesearch_peers_total
        self.remote_counters = {"asked": 0, "skipped_sick": 0,
                                "adaptive_timeout": 0}

    # -- local side ----------------------------------------------------------

    def set_local_counts_fn(self, fn) -> None:
        """Override the local windowed-count source (loopback tests run
        N nodes against ONE process-global histogram registry)."""
        with self._lock:
            self._local_counts_fn = fn
            self._cached = None

    def local_counts(self, family: str) -> list:
        fn = self._local_counts_fn
        if fn is not None:
            got = fn(family)
            return list(got) if got is not None else []
        h = histogram.get(family)
        return h.windowed_counts() if h is not None else []

    def render(self) -> dict:
        """The node's current digest (TTL-cached: gossip may attach it
        to many concurrent RPCs without re-walking the histograms)."""
        now = time.monotonic()
        with self._lock:
            if self._cached is not None and \
                    now - self._cached_mono < self.render_ttl_s:
                return self._cached
            self._seq += 1
            seq = self._seq
        hist: dict[str, dict] = {}
        for fam in DIGEST_FAMILIES:
            counts = self.local_counts(fam)
            if counts and sum(counts) > 0:
                hist[fam] = histogram.counts_to_sparse(counts)
        eng = getattr(self.sb, "health", None)
        rules = {}
        health = 0
        if eng is not None:
            sev = {"ok": 0, "warn": 1, "critical": 2}
            rules = {name: sev.get(st.state, 0)
                     for name, _d, st in eng.rule_table()
                     if not name.startswith("fleet_")}
            health = eng.status_value()
        ds = getattr(self.sb.index, "devstore", None)
        c = ds.counters() if ds is not None else {}
        b = getattr(ds, "_batcher", None) if ds is not None else None
        # multi-process mesh identity (ISSUE 12): the digest names the OS
        # process behind this node — pid always (the CI hygiene gate
        # asserts distinct pids over the wire), mesh process id / fleet
        # size when this node is a jax.distributed mesh member, and its
        # device-lost flag so the coordinator's Network_Health_p renders
        # a REAL multi-process mesh, not a simulated one
        mm = getattr(self.sb, "mesh_member", None)
        import os as _os
        proc = {"pid": _os.getpid(),
                "id": mm.process_id if mm is not None else 0,
                "n": mm.num_processes if mm is not None else 1,
                "lost": (1 if getattr(ds, "device_lost", False) else 0)}
        act = getattr(self.sb, "actuators", None)
        digest = {
            "v": DIGEST_VERSION,
            "peer": self.my_hash,
            "seq": seq,
            "ts": round(time.time(), 1),
            "hist": hist,
            "rules": rules,
            "health": health,
            "cache": {"hits": int(c.get("rank_cache_hits", 0)),
                      "served": int(c.get("queries_served", 0))},
            "queues": {"incoming": b._q.qsize() if b is not None else 0,
                       "inflight": b._inflight.qsize()
                       if b is not None else 0},
            "proc": proc,
            # serving rung + windowed dominant tail cause (ISSUE 15):
            # the fleet sees WHO is degraded and WHY its tail is fat.
            # The cause travels as its INDEX into the tailattr.CAUSES
            # canon (~6 bytes vs ~30 for the label — the digest's
            # byte budget is a wire contract)
            "act": {
                "l": int(act.effective_level())
                if act is not None else 0,
                "c": tailattr.CAUSES.index(tailattr.top_cause()),
                # whitebox top-frame role (ISSUE 20d): which thread
                # role this node burns most samples in, as an index
                # into the zero-filled profiling.ROLES canon — a peer
                # whose dispatcher pool pegs is visible fleet-wide
                # before it straggles (~8 bytes, the act.c model)
                "p": profiling.top_role_index(),
            },
            "epoch": int(c.get("arena_epoch", 0)),
            # tier occupancy in KiB (compact: ~30 B inside the 2 KiB
            # budget) + warm->hot promotions — a peer whose w/c grow
            # while p churns is paging, visible mesh-wide
            "tiers": {
                "h": int(c.get("tier_hot_bytes", 0)) >> 10,
                "w": int(c.get("tier_warm_bytes", 0)) >> 10,
                "c": int(c.get("tier_cold_bytes", 0)) >> 10,
                "p": int(c.get("tier_promotions_warm_hot", 0)),
                # vector-side residency (ISSUE 11): dense f16 forward
                # block + the ANN slab ladder, KiB like the postings
                "d": int(c.get("dense_fwd_bytes", 0)) >> 10,
                "ah": int(c.get("ann_hot_bytes", 0)) >> 10,
                "aw": int(c.get("ann_warm_bytes", 0)) >> 10,
                "ac": int(c.get("ann_cold_bytes", 0)) >> 10,
            },
        }
        # wire budget: a digest must never bloat the exchanges it rides.
        # Dropping the largest family degrades the mesh view gracefully
        # (absent merges as absent); the bench pins that real serving
        # load never trims.
        size = digest_bytes(digest)
        while size > self.byte_budget and digest["hist"]:
            fat = max(digest["hist"],
                      key=lambda f: len(encode_digest(digest["hist"][f])))
            del digest["hist"][fat]
            digest["trimmed"] = 1
            size = digest_bytes(digest)
        with self._lock:
            self.rendered_count += 1
            # two TTL-expired renders can race: only the NEWEST seq may
            # own the cache, or a stale-seq digest would gossip for the
            # next TTL and be dropped by receivers as a replay
            if self._cached is None or seq >= self._cached.get("seq", 0):
                self._cached = digest
                self._cached_mono = now
                self.last_digest_bytes = size
        return digest

    def outgoing_digest(self, peer_hash) -> dict | None:
        """The digest to piggyback on an RPC to `peer_hash`, or None if
        that peer got one inside `fleet.sendIntervalS` (the per-peer
        rate limit that keeps gossip amortized over existing traffic)."""
        if not self.enabled:
            return None
        key = peer_key(peer_hash)
        now = time.monotonic()
        with self._lock:
            if now - self._sent.get(key, -1e9) < self.send_interval_s:
                return None
            self._sent[key] = now
        return self.render()

    def send_failed(self, peer_hash) -> None:
        """Release the rate-limit slot `outgoing_digest` charged for an
        RPC that then failed: the digest never arrived, so the next
        successful exchange with that peer should carry one instead of
        waiting out `fleet.sendIntervalS` on a phantom delivery."""
        key = peer_key(peer_hash)
        with self._lock:
            self._sent.pop(key, None)

    # -- receive side --------------------------------------------------------

    def ingest(self, digest) -> bool:
        """Store a peer's digest.  Tolerant by contract: unknown fields
        are ignored, malformed histogram families are dropped
        individually, missing families stay absent.  Rejected outright
        (counted in `ignored_count`): non-dict payloads, digests without
        a peer hash, our own digest reflected back, and per-peer
        seq/ts replays."""
        if not self.enabled or not isinstance(digest, dict):
            self._ignore()
            return False
        peer = digest.get("peer")
        if not isinstance(peer, str) or not peer or peer == self.my_hash:
            self._ignore()
            return False
        try:
            seq = int(digest.get("seq", 0))
            ts = float(digest.get("ts", 0.0))
        except (TypeError, ValueError):
            self._ignore()
            return False
        # The wire is unauthenticated (the same trust level as seed
        # gossip itself), so digest CONTENT is only as trustworthy as
        # the mesh — but a forged future `ts` must never lock a
        # victim's real digests out of the replay gate below.  Two
        # guards: egregiously future timestamps are rejected outright,
        # and every ACCEPTED ts is CLAMPED to the receiver's clock —
        # so no stored ts ever exceeds its ingest time, and a genuine
        # later digest (fresh ts > any past ingest time) always passes
        # `ts > prev.ts` no matter what an attacker stored first.
        if ts > time.time() + MAX_TS_SKEW_S:
            self._ignore()
            return False
        ts = min(ts, time.time())
        hist: dict[str, list] = {}
        raw_hist = digest.get("hist")
        if isinstance(raw_hist, dict):
            for fam, sp in raw_hist.items():
                counts = histogram.counts_from_sparse(sp)
                if counts is not None:
                    hist[str(fam)] = counts
        rules: dict[str, int] = {}
        raw_rules = digest.get("rules")
        if isinstance(raw_rules, dict):
            for name, v in raw_rules.items():
                if isinstance(v, int) and 0 <= v <= 2:
                    rules[str(name)] = v
        entry = {
            "peer": peer,
            "seq": seq,
            "ts": ts,
            "hist": hist,
            "rules": rules,
            "health": digest.get("health")
            if digest.get("health") in (0, 1, 2) else 0,
            "cache": digest.get("cache")
            if isinstance(digest.get("cache"), dict) else {},
            "queues": digest.get("queues")
            if isinstance(digest.get("queues"), dict) else {},
            "epoch": digest.get("epoch")
            if isinstance(digest.get("epoch"), int) else 0,
            "proc": digest.get("proc")
            if isinstance(digest.get("proc"), dict) else {},
            "act": digest.get("act")
            if isinstance(digest.get("act"), dict) else {},
            "recv_mono": time.monotonic(),
            "recv_ts": time.time(),
            "bytes": digest_bytes(digest),
        }
        with self._lock:
            prev = self._peers.get(peer)
            if prev is not None and seq <= prev["seq"] and ts <= prev["ts"]:
                self.ignored_count += 1     # replay / out-of-order
                return False
            self._peers[peer] = entry
            self.received_count += 1
        self.evict_stale()
        return True

    def _ignore(self) -> None:
        with self._lock:
            self.ignored_count += 1

    def note_rtt(self, peer_hash, ms: float) -> None:
        """Last observed RPC wall against this peer (remote searches,
        DHT transfers) — the peer table's liveness column."""
        key = peer_key(peer_hash)
        with self._lock:
            self._rtt_ms[key] = (float(ms), time.monotonic())

    def evict_stale(self, now: float | None = None) -> int:
        """Drop digests older than `fleet.staleS` — a silent peer leaves
        the merged view (absence, not zeros).  The per-peer send/RTT
        bookkeeping ages out on the same horizon, so a churning open
        mesh never grows these maps without bound."""
        now = time.monotonic() if now is None else now
        with self._lock:
            # every read path (fresh/merged_counts/peer_rows) drives
            # eviction, so one scrape or health tick would re-scan these
            # maps ~10 times within milliseconds; against a 300s
            # staleness horizon that is pure lock-held waste — time-gate
            # re-scans (scaled down with stale_s so tests that shrink
            # the horizon still evict immediately)
            if now - self._last_evict < min(1.0, self.stale_s / 10.0):
                return 0
            self._last_evict = now
            dead = [h for h, e in self._peers.items()
                    if now - e["recv_mono"] > self.stale_s]
            for h in dead:
                del self._peers[h]
            horizon = max(self.stale_s, self.send_interval_s)
            for h in [h for h, t in self._sent.items()
                      if now - t > horizon]:
                del self._sent[h]
            for h in [h for h, (_ms, t) in self._rtt_ms.items()
                      if now - t > self.stale_s]:
                del self._rtt_ms[h]
        return len(dead)

    def fresh(self) -> list:
        """Current (non-stale) peer digest entries, stably ordered."""
        self.evict_stale()
        with self._lock:
            return [self._peers[h] for h in sorted(self._peers)]

    # -- the mesh view -------------------------------------------------------

    def merged_counts(self, family: str) -> list:
        """Mesh-wide bucket vector: own windowed counts + every fresh
        peer's digest vector.  Lossless by construction (integer sums on
        one shared bucket grid), so the percentile any node computes
        from it is EXACTLY the percentile over the union of samples."""
        vecs = []
        own = self.local_counts(family)
        if own:
            vecs.append(own)
        for e in self.fresh():
            counts = e["hist"].get(family)
            if counts is not None:          # absent stays absent
                vecs.append(counts)
        return histogram.merge_counts(vecs) if vecs \
            else [0] * histogram.N_BUCKETS

    def mesh_percentile(self, family: str, q: float) -> float:
        return histogram.percentile_from_counts(
            self.merged_counts(family), q)

    def critical_peers(self) -> list:
        return [e["peer"] for e in self.fresh() if e.get("health") == 2]

    # -- remote-search actuation surface (ISSUE 9) ---------------------------

    def note_remote(self, event: str, n: int = 1) -> None:
        """Count one remote-search actuation decision (asked /
        skipped_sick / adaptive_timeout) — the counters that attribute
        every peer skip in `/metrics`."""
        with self._lock:
            if event in self.remote_counters:
                self.remote_counters[event] += n

    def remote_counter_snapshot(self) -> dict:
        with self._lock:
            return dict(self.remote_counters)

    def sick_peers(self, outlier_factor: float = 3.0,
                   min_mesh: int = 50, min_peer: int = 20) -> list:
        """Peer hashes the remote scatter should avoid: digests
        reporting critical health or a wedged kernel, plus serving-p95
        outliers judged leave-one-out against the rest of the mesh (the
        fleet_peer_outlier rule's discipline — a high-traffic outlier
        must not mask itself inside the merged tail).  `min_mesh`/
        `min_peer` are the SAME statistical gates the rule applies
        (health.fleetOutlierMinSamples / MinPeerSamples — callers pass
        the configured values so the actuation never judges data the
        diagnostic layer would refuse to judge); the digest-reported
        critical/stall verdicts are explicit, not statistical, and
        stay ungated."""
        fresh = self.fresh()
        if not fresh:
            return []
        sick: set[str] = set()
        for e in fresh:
            if e.get("health") == 2 or \
                    e.get("rules", {}).get("worker_stall") == 2:
                sick.add(e["peer"])
        merged = self.merged_counts("servlet.serving")
        if sum(merged) < min_mesh:
            return sorted(sick)     # insufficient mesh traffic for the
            #                         outlier verdict (rule parity)
        for e in fresh:
            counts = e["hist"].get("servlet.serving")
            if e["peer"] in sick or not counts \
                    or sum(counts) < min_peer:
                continue        # thin family: no verdict
            rest = [max(0, m - c) for m, c in zip(merged, counts)]
            if sum(rest) < min_peer:
                continue        # no baseline to judge against
            p95 = histogram.percentile_from_counts(counts, 0.95)
            rest_p95 = histogram.percentile_from_counts(rest, 0.95)
            if p95 > outlier_factor * rest_p95:
                sick.add(e["peer"])
        return sorted(sick)

    def peer_rpc_p95_ms(self, peer_hash,
                        min_samples: int = 20) -> float | None:
        """This peer's digest-reported RPC wall p95 (`dht.transfer`
        family); None for digest-less peers or digests with fewer than
        `min_samples` observations — the caller keeps its static
        timeout for those.  Same statistical discipline as sick_peers:
        actuation never judges data thinner than the diagnostic layer
        would accept (one fast RPC must not collapse a healthy peer's
        search timeout)."""
        key = peer_key(peer_hash)
        with self._lock:
            entry = self._peers.get(key)
        if entry is not None:
            counts = entry["hist"].get("dht.transfer")
            if counts and sum(counts) >= min_samples:
                return histogram.percentile_from_counts(counts, 0.95)
        return None

    def peer_rows(self) -> list:
        """Per-peer table rows for `Network_Health_p`: state, windowed
        percentiles per digest family (None where the family is absent
        — version skew shows as '-', never as fake zeros), staleness
        age, sequence number and wire size."""
        now = time.monotonic()
        rows = []
        fresh = self.fresh()
        with self._lock:
            rtts = dict(self._rtt_ms)
        for e in fresh:
            got = rtts.get(e["peer"])
            rtt = got[0] if got is not None else None
            quantiles = {}
            for fam in DIGEST_FAMILIES:
                counts = e["hist"].get(fam)
                if counts is None or sum(counts) == 0:
                    quantiles[fam] = None
                else:
                    quantiles[fam] = tuple(
                        histogram.percentile_from_counts(counts, q)
                        for q in (0.50, 0.95, 0.99))
            rows.append({
                "hash": e["peer"],
                "health": e.get("health", 0),
                "state": STATE_NAMES[e.get("health", 0)],
                "age_s": round(now - e["recv_mono"], 1),
                "seq": e["seq"],
                "bytes": e["bytes"],
                "rtt_ms": rtt,
                "quantiles": quantiles,
                "queues": e.get("queues", {}),
                "epoch": e.get("epoch", 0),
                "proc": e.get("proc", {}),
                # serving rung + tail-cause top-1 (ISSUE 15 satellite),
                # decoded for Network_Health_p's degraded-member columns
                "act": ({"lvl": e["act"].get("l", 0),
                         "cause": decode_act_cause(e["act"])}
                        if e.get("act") else {}),
            })
        return rows
