"""Config/data migrations across versions.

Capability equivalent of the reference's migration module (reference:
source/net/yacy/migration.java — version-gated config rewrites run once
at startup, yacy.java:285). Steps are (from_version, fn) pairs applied in
order when the stored config version is older; the stored version is then
bumped to the current release.
"""

from __future__ import annotations


def _v(version: str) -> tuple[int, ...]:
    try:
        return tuple(int(p) for p in version.split("."))
    except ValueError:
        return (0,)


def _m_0_1_0(config) -> None:
    """0.1.0: heuristics default off; scheduler enabled."""
    if not config.get("heuristic.site"):
        config.set("heuristic.site", "false")


def _m_0_2_0(config) -> None:
    """0.2.0: network unit selection key introduced."""
    if not config.get("network.unit.definition"):
        config.set("network.unit.definition", "freeworld")


MIGRATIONS: list[tuple[str, object]] = [
    ("0.1.0", _m_0_1_0),
    ("0.2.0", _m_0_2_0),
]


def migrate(config, current_version: str) -> int:
    """Apply every step newer than the stored version; returns steps run."""
    stored = config.get("version", "0.0.0")
    ran = 0
    for step_version, fn in MIGRATIONS:
        if _v(stored) < _v(step_version) <= _v(current_version):
            fn(config)
            ran += 1
    if stored != current_version:
        config.set("version", current_version)
    return ran


# -- data-store migrations ---------------------------------------------------
# Stores churn independently of config (profiles jsonl, runs manifest,
# metadata journal); each step upgrades rows written by an older release
# in place. Applied once per store version bump at Switchboard startup.


def _d_backfill_signatures(segment) -> int:
    """0.3.0: exact/fuzzy content signatures were added to the schema —
    rows journaled by older releases replay with the 0 sentinel and
    never participate in duplicate detection. Backfill them from the
    stored text."""
    from .document.signature import exact_signature, fuzzy_signature
    meta = segment.metadata
    fixed = 0
    for docid in range(meta.capacity()):
        if meta.is_deleted(docid):
            continue
        row = meta.row(docid)
        if row.get("exact_signature_l", 0):
            continue
        text = row.get("text_t", "")
        if not text:
            continue
        meta.set_fields(docid,
                        exact_signature_l=exact_signature(text),
                        fuzzy_signature_l=fuzzy_signature(text))
        fixed += 1
    return fixed


def _d_backfill_url_protocol(segment) -> int:
    """0.3.1: url_protocol_s feeds the protocol: modifier's facet index —
    derive it from the stored url for rows written by older releases."""
    meta = segment.metadata
    fixed = 0
    for docid in range(meta.capacity()):
        if meta.is_deleted(docid):
            continue
        row = meta.row(docid)
        if row.get("url_protocol_s", ""):
            continue
        sku = row.get("sku", "")
        scheme = sku.split("://", 1)[0].lower() if "://" in sku else ""
        if scheme:
            meta.set_fields(docid, url_protocol_s=scheme)
            fixed += 1
    return fixed


def _d_reencode_dense(segment) -> int:
    """0.3.2: the dense feature hash changed (ENCODER_VERSION 2) —
    vectors stored under the old hash are incomparable with current
    query vectors, so re-encode every live document from its stored
    text. Embeddings are derivable data; the store marks itself stale
    when its persisted encoder version is older."""
    dense = segment.dense
    if not getattr(dense, "stale_encoder", False):
        return 0
    meta = segment.metadata
    fixed = 0
    for docid in range(min(meta.capacity(), len(dense))):
        if meta.is_deleted(docid):
            continue
        row = meta.row(docid)
        text = f"{row.get('title', '')}\n{row.get('text_t', '')[:4096]}"
        dense.put(docid, segment.encoder.encode(text))
        fixed += 1
    dense.mark_encoder_current()   # persist + stamp: migration complete
    return fixed


DATA_MIGRATIONS: list[tuple[str, object]] = [
    ("0.3.0", _d_backfill_signatures),
    # 0.3.1, not 0.3.0: stores started by a 0.3.0 build already carry
    # STORE_VERSION=0.3.0 and would skip a step registered there
    ("0.3.1", _d_backfill_url_protocol),
    ("0.3.2", _d_reencode_dense),
]


def migrate_data(segment, data_dir: str, current_version: str) -> int:
    """Apply data-store migration steps newer than the stored data
    version; returns rows touched. The version marker lives IN the data
    dir (STORE_VERSION file), not in config: the data's age travels with
    the data when an operator copies a DATA dir between releases, and it
    cannot be masked by the config migration bumping its own version
    first (nor forgotten when a caller holds a throwaway config)."""
    import os
    marker = os.path.join(data_dir, "STORE_VERSION")
    stored = "0.0.0"
    if os.path.exists(marker):
        with open(marker, encoding="ascii") as f:
            stored = f.read().strip() or "0.0.0"
    touched = 0
    for step_version, fn in DATA_MIGRATIONS:
        if _v(stored) < _v(step_version) <= _v(current_version):
            touched += fn(segment)
    if stored != current_version:
        tmp = marker + ".tmp"
        with open(tmp, "w", encoding="ascii") as f:
            f.write(current_version)
        os.replace(tmp, marker)
    return touched
