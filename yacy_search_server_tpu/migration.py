"""Config/data migrations across versions.

Capability equivalent of the reference's migration module (reference:
source/net/yacy/migration.java — version-gated config rewrites run once
at startup, yacy.java:285). Steps are (from_version, fn) pairs applied in
order when the stored config version is older; the stored version is then
bumped to the current release.
"""

from __future__ import annotations


def _v(version: str) -> tuple[int, ...]:
    try:
        return tuple(int(p) for p in version.split("."))
    except ValueError:
        return (0,)


def _m_0_1_0(config) -> None:
    """0.1.0: heuristics default off; scheduler enabled."""
    if not config.get("heuristic.site"):
        config.set("heuristic.site", "false")


def _m_0_2_0(config) -> None:
    """0.2.0: network unit selection key introduced."""
    if not config.get("network.unit.definition"):
        config.set("network.unit.definition", "freeworld")


MIGRATIONS: list[tuple[str, object]] = [
    ("0.1.0", _m_0_1_0),
    ("0.2.0", _m_0_2_0),
]


def migrate(config, current_version: str) -> int:
    """Apply every step newer than the stored version; returns steps run."""
    stored = config.get("version", "0.0.0")
    ran = 0
    for step_version, fn in MIGRATIONS:
        if _v(stored) < _v(step_version) <= _v(current_version):
            fn(config)
            ran += 1
    if stored != current_version:
        config.set("version", current_version)
    return ran
