"""Immutable columnar segment files — the metadata store's disk format.

The paging engine under ``MetadataStore`` and ``WebgraphStore`` (VERDICT
r2 missing #2): the same shape ``pagedrun.py`` gave postings, applied to
document/edge columns. One ``.seg`` file holds a frozen range of rows as
raw column blobs addressed by a JSON header; every column opens as an
``np.memmap`` (numeric / fixed-width) or as an (offsets, blob) pair
(variable-width text), so reading a row touches only the pages that row
lives on — RSS stays bounded by the OS page cache, not by index size.

This replaces the grow-forever JSONL journal as the store of record
(reference analogy: the metadata store is Solr/Lucene, on disk by
construction — source/net/yacy/search/index/Fulltext.java:90-230,
kelondro/blob/HeapReader.java:60 for the header-then-payload file
shape). The journal survives only as the TAIL: rows newer than the last
snapshot, replayed at open in O(tail).

File layout (all little-endian):

    8 bytes   magic  b"YTCS0001"
    8 bytes   uint64 header length H
    H bytes   JSON header:
                n            row count
                arrays       name -> {dtype, shape, off}
                texts        name -> {ioff, blob_off, blob_len}
                meta         caller-owned JSON blob (facet tables, ...)
    payload   raw column data (8-byte aligned blobs)

Text columns store UTF-8 blobs with a uint64 offsets array [n+1]; row i
decodes blob[offsets[i]:offsets[i+1]].
"""

from __future__ import annotations

import json
import os

import numpy as np

MAGIC = b"YTCS0001"
_ALIGN = 8


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename/create inside it is durable — an
    os.replace alone orders nothing on power loss; the store-everything
    contract (reference IndexCell.java:115) needs the direntry on disk.
    Best-effort: platforms without directory fds (Windows) skip it."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def purge_stale_journals(data_dir: str, prefix: str, keep: str) -> None:
    """Delete `<prefix>.jsonl` / `<prefix>.NNNNNN.jsonl` journal
    generations the manifest no longer references (shared by the
    metadata and webgraph stores — the generation-name pattern must
    never diverge between them)."""
    import re
    pat = re.compile(rf"^{re.escape(prefix)}(\.\d{{6}})?\.jsonl$")
    try:
        for name in os.listdir(data_dir):
            if pat.match(name) and name != keep:
                try:
                    os.remove(os.path.join(data_dir, name))
                except OSError:
                    pass
    except OSError:
        pass


def write_durable(path: str, data: bytes | str,
                  encoding: str | None = None) -> None:
    """tmp + fsync + rename + dir-fsync in one place: the crash-ordering
    idiom every manifest/state file in the index uses. The tmp name is
    process-unique — two processes snapshotting the same store must
    last-writer-win, not crash each other's rename."""
    tmp = f"{path}.tmp{os.getpid()}"
    mode = "wb" if encoding is None else "w"
    with open(tmp, mode, encoding=encoding) as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def write_segment(path: str, n: int,
                  arrays: dict[str, np.ndarray],
                  texts: dict[str, list[str]],
                  meta: dict | None = None) -> None:
    """Write a frozen segment atomically (tmp + rename)."""
    header: dict = {"n": int(n), "arrays": {}, "texts": {},
                    "meta": meta or {}}
    blobs: list[bytes] = []
    off = 0

    def add_blob(b: bytes) -> int:
        nonlocal off
        start = off
        blobs.append(b)
        pad = _pad(len(b)) - len(b)
        if pad:
            blobs.append(b"\0" * pad)
        off += _pad(len(b))
        return start

    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        header["arrays"][name] = {
            "dtype": arr.dtype.str, "shape": list(arr.shape),
            "off": add_blob(arr.tobytes())}
    for name, col in texts.items():
        if len(col) != n:
            raise ValueError(f"text column {name}: {len(col)} rows != {n}")
        offsets = np.zeros(n + 1, np.uint64)
        parts = []
        pos = 0
        for i, s in enumerate(col):
            b = (s or "").encode("utf-8")
            parts.append(b)
            pos += len(b)
            offsets[i + 1] = pos
        blob = b"".join(parts)
        header["texts"][name] = {
            "ioff": add_blob(offsets.tobytes()),
            "blob_off": add_blob(blob), "blob_len": len(blob)}

    hbytes = json.dumps(header).encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(np.uint64(len(hbytes)).tobytes())
        f.write(hbytes)
        base = f.tell()
        pad = _pad(base) - base
        if pad:
            f.write(b"\0" * pad)
        for b in blobs:
            f.write(b)
        # durability before visibility: rename must never publish a
        # segment whose pages are still only in the page cache (power
        # loss would leave a zero-length or torn file behind the name)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


class SegmentReader:
    """mmap view of one segment file; columns open lazily and cache."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            if f.read(8) != MAGIC:
                raise ValueError(f"not a segment file: {path}")
            hlen = int(np.frombuffer(f.read(8), np.uint64)[0])
            self.header = json.loads(f.read(hlen).decode("utf-8"))
            self._payload = _pad(f.tell())
        self.n: int = self.header["n"]
        self.meta: dict = self.header.get("meta", {})
        self._arrays: dict[str, np.memmap] = {}
        self._texts: dict[str, tuple] = {}

    def array(self, name: str) -> np.ndarray:
        got = self._arrays.get(name)
        if got is None:
            spec = self.header["arrays"][name]
            got = np.memmap(self.path, mode="r",
                            dtype=np.dtype(spec["dtype"]),
                            shape=tuple(spec["shape"]),
                            offset=self._payload + spec["off"])
            self._arrays[name] = got
        return got

    def has_array(self, name: str) -> bool:
        return name in self.header["arrays"]

    def has_text(self, name: str) -> bool:
        return name in self.header["texts"]

    def _text_maps(self, name: str):
        got = self._texts.get(name)
        if got is None:
            spec = self.header["texts"][name]
            offsets = np.memmap(self.path, mode="r", dtype=np.uint64,
                                shape=(self.n + 1,),
                                offset=self._payload + spec["ioff"])
            blob = (np.empty(0, np.uint8) if spec["blob_len"] == 0
                    else np.memmap(self.path, mode="r", dtype=np.uint8,
                                   shape=(spec["blob_len"],),
                                   offset=self._payload + spec["blob_off"]))
            got = (offsets, blob)
            self._texts[name] = got
        return got

    def text(self, name: str, i: int) -> str:
        offsets, blob = self._text_maps(name)
        lo, hi = int(offsets[i]), int(offsets[i + 1])
        if lo == hi:
            return ""
        return bytes(blob[lo:hi]).decode("utf-8", "replace")

    def texts_at(self, name: str, rows: np.ndarray) -> list[str]:
        """Batched text reads: ONE fancy-indexed offsets lookup instead
        of per-row python (the navigator/drain hot path reads several
        fields x ~80 candidates per query)."""
        offsets, blob = self._text_maps(name)
        rows = np.asarray(rows, np.int64)
        lo = np.asarray(offsets[rows], np.int64)
        hi = np.asarray(offsets[rows + 1], np.int64)
        return [("" if a == b else
                 bytes(blob[a:b]).decode("utf-8", "replace"))
                for a, b in zip(lo.tolist(), hi.tolist())]

    def text_column(self, name: str) -> list[str]:
        """Materialize a whole text column (compaction path)."""
        offsets, blob = self._text_maps(name)
        raw = bytes(blob[: int(offsets[-1])])
        offs = np.asarray(offsets)
        return [raw[int(offs[i]):int(offs[i + 1])].decode("utf-8", "replace")
                for i in range(self.n)]

    def close(self) -> None:
        self._arrays.clear()
        self._texts.clear()
