"""Immutable columnar segment files — the metadata store's disk format.

The paging engine under ``MetadataStore`` and ``WebgraphStore`` (VERDICT
r2 missing #2): the same shape ``pagedrun.py`` gave postings, applied to
document/edge columns. One ``.seg`` file holds a frozen range of rows as
raw column blobs addressed by a JSON header; every column opens as an
``np.memmap`` (numeric / fixed-width) or as an (offsets, blob) pair
(variable-width text), so reading a row touches only the pages that row
lives on — RSS stays bounded by the OS page cache, not by index size.

This replaces the grow-forever JSONL journal as the store of record
(reference analogy: the metadata store is Solr/Lucene, on disk by
construction — source/net/yacy/search/index/Fulltext.java:90-230,
kelondro/blob/HeapReader.java:60 for the header-then-payload file
shape). The journal survives only as the TAIL: rows newer than the last
snapshot, replayed at open in O(tail).

File layout (all little-endian):

    8 bytes   magic  b"YTCS0001"
    8 bytes   uint64 header length H
    H bytes   JSON header:
                n            row count
                arrays       name -> {dtype, shape, off}
                texts        name -> {ioff, blob_off, blob_len}
                meta         caller-owned JSON blob (facet tables, ...)
    payload   raw column data (8-byte aligned blobs)

Text columns store UTF-8 blobs with a uint64 offsets array [n+1]; row i
decodes blob[offsets[i]:offsets[i+1]].
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..utils import faultinject
from . import integrity

MAGIC = b"YTCS0001"
_ALIGN = 8


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename/create inside it is durable — an
    os.replace alone orders nothing on power loss; the store-everything
    contract (reference IndexCell.java:115) needs the direntry on disk.
    Best-effort: platforms without directory fds (Windows) skip it."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def purge_stale_journals(data_dir: str, prefix: str, keep: str) -> None:
    """Delete `<prefix>.jsonl` / `<prefix>.NNNNNN.jsonl` journal
    generations the manifest no longer references (shared by the
    metadata and webgraph stores — the generation-name pattern must
    never diverge between them)."""
    import re
    pat = re.compile(rf"^{re.escape(prefix)}(\.\d{{6}})?\.jsonl$")
    try:
        for name in os.listdir(data_dir):
            if pat.match(name) and name != keep:
                try:
                    os.remove(os.path.join(data_dir, name))
                except OSError:
                    pass
    except OSError:
        pass


def write_durable(path: str, data: bytes | str,
                  encoding: str | None = None) -> None:
    """tmp + fsync + rename + dir-fsync in one place: the crash-ordering
    idiom every manifest/state file in the index uses. The tmp name is
    process-unique — two processes snapshotting the same store must
    last-writer-win, not crash each other's rename."""
    tmp = f"{path}.tmp{os.getpid()}"
    mode = "wb" if encoding is None else "w"
    faultinject.io_error(path)
    torn = faultinject.torn_write_bytes(path)
    with open(tmp, mode, encoding=encoding) as f:
        if torn is not None:
            # chaos harness: the on-disk artifact of a crash mid-write —
            # a truncated .tmp that never reaches the rename below.
            # Truncation is in BYTES on the raw fd (a str slice would
            # always land on a character boundary, cleaner than a real
            # kill−9 tear through a multi-byte sequence)
            raw = (data.encode(encoding or "utf-8")
                   if isinstance(data, str) else data)
            f.flush()
            os.write(f.fileno(), raw[:max(0, torn)])
            f.flush()
            raise faultinject.InjectedFault(
                f"injected io.torn_write on {path}")
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def journal_append(f, payload: str, sync: bool = True,
                   checksum: bool = True) -> None:
    """THE shared journal-append path (ISSUE 10 satellite): crc-prefixed
    line + flush + fsync on one code path, instead of the bare
    ``write(); flush()`` several stores grew independently — an append
    that returns is on the platter, so the crash-ordering guarantees
    the manifests state actually hold on every journal.  `checksum`
    prefixes the line with its crc32 (``integrity.crc_line``); replays
    strip it with ``integrity.check_line`` and still read legacy
    prefix-free lines."""
    name = getattr(f, "name", "")
    line = (integrity.crc_line(payload) if checksum else payload) + "\n"
    faultinject.io_error(name)
    torn = faultinject.torn_write_bytes(name)
    if torn is not None:
        # the torn-tail artifact: a partial line at EOF, then "crash".
        # BYTE-accurate (raw fd write): a real tear can land mid-way
        # through a multi-byte character, and the recovery path must
        # face exactly that
        f.flush()
        os.write(f.fileno(), line.encode("utf-8")[:max(0, torn)])
        f.flush()
        raise faultinject.InjectedFault(
            f"injected io.torn_write on {name}")
    f.write(line)
    f.flush()
    if sync:
        os.fsync(f.fileno())


def journal_append_many(f, payloads, sync: bool = True,
                        checksum: bool = True) -> None:
    """Batch form of :func:`journal_append`: one flush+fsync for a
    whole batch of records (the webgraph writes one journal line per
    edge — per-line fsync would turn an add_document_edges batch into
    dozens of disk barriers for one durability point)."""
    name = getattr(f, "name", "")
    faultinject.io_error(name)
    for payload in payloads:
        f.write((integrity.crc_line(payload) if checksum else payload)
                + "\n")
    f.flush()
    if sync:
        os.fsync(f.fileno())


def write_segment(path: str, n: int,
                  arrays: dict[str, np.ndarray],
                  texts: dict[str, list[str]],
                  meta: dict | None = None) -> None:
    """Write a frozen segment atomically (tmp + rename)."""
    header: dict = {"n": int(n), "arrays": {}, "texts": {},
                    "meta": meta or {}}
    blobs: list[bytes] = []
    off = 0

    def add_blob(b: bytes) -> int:
        nonlocal off
        start = off
        blobs.append(b)
        pad = _pad(len(b)) - len(b)
        if pad:
            blobs.append(b"\0" * pad)
        off += _pad(len(b))
        return start

    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        header["arrays"][name] = {
            "dtype": arr.dtype.str, "shape": list(arr.shape),
            "off": add_blob(raw), "crc": integrity.crc32(raw)}
    for name, col in texts.items():
        if len(col) != n:
            raise ValueError(f"text column {name}: {len(col)} rows != {n}")
        offsets = np.zeros(n + 1, np.uint64)
        parts = []
        pos = 0
        for i, s in enumerate(col):
            b = (s or "").encode("utf-8")
            parts.append(b)
            pos += len(b)
            offsets[i + 1] = pos
        blob = b"".join(parts)
        oraw = offsets.tobytes()
        header["texts"][name] = {
            "ioff": add_blob(oraw),
            "blob_off": add_blob(blob), "blob_len": len(blob),
            "crc": integrity.crc32(blob, integrity.crc32(oraw))}

    hbytes = json.dumps(header).encode("utf-8")
    tmp = path + ".tmp"
    faultinject.io_error(path)
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(np.uint64(len(hbytes)).tobytes())
        f.write(hbytes)
        # chaos barrier: payload only partially written — the .tmp never
        # reaches the rename, so the store's visible state is unchanged
        faultinject.crashpoint("colstore.segment.mid_write")
        base = f.tell()
        pad = _pad(base) - base
        if pad:
            f.write(b"\0" * pad)
        for b in blobs:
            f.write(b)
        # durability before visibility: rename must never publish a
        # segment whose pages are still only in the page cache (power
        # loss would leave a zero-length or torn file behind the name)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


class SegmentReader:
    """mmap view of one segment file; columns open lazily and cache."""

    def __init__(self, path: str):
        self.path = path
        # open scrub (ISSUE 10): magic + parseable header + every blob
        # extent inside the file — a truncated/garbage segment becomes a
        # typed CorruptSegmentError at open, never a struct/mmap crash
        # inside a later query
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                if f.read(8) != MAGIC:
                    raise integrity.CorruptSegmentError(
                        f"not a segment file: {path}")
                hlen = int(np.frombuffer(f.read(8), np.uint64)[0])
                self.header = json.loads(f.read(hlen).decode("utf-8"))
                self._payload = _pad(f.tell())
            for name, spec in self.header["arrays"].items():
                nbytes = int(np.prod(spec["shape"]) or 1) * \
                    np.dtype(spec["dtype"]).itemsize
                if self._payload + spec["off"] + nbytes > size:
                    raise integrity.CorruptSegmentError(
                        f"{path}: array {name} extends past EOF")
            for name, spec in self.header["texts"].items():
                if self._payload + spec["blob_off"] \
                        + spec["blob_len"] > size:
                    raise integrity.CorruptSegmentError(
                        f"{path}: text {name} extends past EOF")
        except integrity.CorruptSegmentError:
            integrity.note_corruption("segment", "error")
            raise
        except (OSError, ValueError, KeyError, OverflowError,
                MemoryError, json.JSONDecodeError) as e:
            integrity.note_corruption("segment", "error")
            raise integrity.CorruptSegmentError(
                f"corrupt segment {path}: {e!r}") from e
        self.n: int = self.header["n"]
        self.meta: dict = self.header.get("meta", {})
        self._arrays: dict[str, np.memmap] = {}
        self._texts: dict[str, tuple] = {}

    def array(self, name: str) -> np.ndarray:
        got = self._arrays.get(name)
        if got is None:
            spec = self.header["arrays"][name]
            got = np.memmap(self.path, mode="r",
                            dtype=np.dtype(spec["dtype"]),
                            shape=tuple(spec["shape"]),
                            offset=self._payload + spec["off"])
            # lazy verify-on-read: ONE pass when the column first pages
            # in for this reader, not per access (columns are immutable;
            # a reopened reader re-verifies).  A content mismatch SERVES
            # DEGRADED (counted + logged) instead of raising: segments
            # have no redundant generation to quarantine to, the open
            # scrub already proved the extents structurally safe to
            # read, and raising here would turn every query touching
            # the column into a permanent 500 — the opposite of the
            # degrade-gracefully contract.  The storage_corruption
            # rule's critical edge still dumps the incident.
            if integrity.VERIFY_ON_READ and "crc" in spec:
                if integrity.crc_arrays(np.ascontiguousarray(got)) \
                        != spec["crc"]:
                    integrity.note_corruption("segment",
                                              "served_degraded")
                    import logging
                    logging.getLogger("yacy.colstore").error(
                        "%s: column %s checksum mismatch — serving "
                        "degraded", self.path, name)
                else:
                    integrity.note_verified()
            self._arrays[name] = got
        return got

    def has_array(self, name: str) -> bool:
        return name in self.header["arrays"]

    def has_text(self, name: str) -> bool:
        return name in self.header["texts"]

    def _text_maps(self, name: str):
        got = self._texts.get(name)
        if got is None:
            spec = self.header["texts"][name]
            offsets = np.memmap(self.path, mode="r", dtype=np.uint64,
                                shape=(self.n + 1,),
                                offset=self._payload + spec["ioff"])
            blob = (np.empty(0, np.uint8) if spec["blob_len"] == 0
                    else np.memmap(self.path, mode="r", dtype=np.uint8,
                                   shape=(spec["blob_len"],),
                                   offset=self._payload + spec["blob_off"]))
            if integrity.VERIFY_ON_READ and "crc" in spec:
                got_crc = integrity.crc_arrays(
                    np.ascontiguousarray(offsets),
                    np.ascontiguousarray(blob))
                if got_crc != spec["crc"]:
                    # served degraded, never a query crash (see array())
                    integrity.note_corruption("segment",
                                              "served_degraded")
                    import logging
                    logging.getLogger("yacy.colstore").error(
                        "%s: text column %s checksum mismatch — "
                        "serving degraded", self.path, name)
                else:
                    integrity.note_verified()
            got = (offsets, blob)
            self._texts[name] = got
        return got

    def text(self, name: str, i: int) -> str:
        offsets, blob = self._text_maps(name)
        lo, hi = int(offsets[i]), int(offsets[i + 1])
        if lo == hi:
            return ""
        return bytes(blob[lo:hi]).decode("utf-8", "replace")

    def texts_at(self, name: str, rows: np.ndarray) -> list[str]:
        """Batched text reads: ONE fancy-indexed offsets lookup instead
        of per-row python (the navigator/drain hot path reads several
        fields x ~80 candidates per query)."""
        offsets, blob = self._text_maps(name)
        rows = np.asarray(rows, np.int64)
        lo = np.asarray(offsets[rows], np.int64)
        hi = np.asarray(offsets[rows + 1], np.int64)
        return [("" if a == b else
                 bytes(blob[a:b]).decode("utf-8", "replace"))
                for a, b in zip(lo.tolist(), hi.tolist())]

    def text_column(self, name: str) -> list[str]:
        """Materialize a whole text column (compaction path)."""
        offsets, blob = self._text_maps(name)
        raw = bytes(blob[: int(offsets[-1])])
        offs = np.asarray(offsets)
        return [raw[int(offs[i]):int(offs[i + 1])].decode("utf-8", "replace")
                for i in range(self.n)]

    def close(self) -> None:
        self._arrays.clear()
        self._texts.clear()
