"""Read-side integrity — checksum footers, corruption accounting,
torn-tail accounting (ISSUE 10 tentpole a).

Five rounds of durability work made every store WRITE carefully (tmp +
fsync + rename + dir-fsync, journal generations, manifest ordering) and
then trusted every READ blindly: a flipped bit in a paged run's mmap, a
truncated ``.tix``, or a torn segment blob would surface as an
unhandled struct/mmap crash inside a query — the exact opposite of the
degrade-gracefully contract the north star needs.  This module is the
shared substrate:

- **crc32 footers** (zlib — already in-tree, no new deps) on every
  durable artifact: per-term-span checksums in ``PagedRun`` ``.tix``
  files (verified lazily when a span materializes off the mmap),
  per-column checksums in colstore segment headers (verified once per
  reader, on first touch), and crc-prefixed journal lines
  (``<crc8hex> <payload>``) on the metadata/webgraph/rwi journals.
- **verify switch**: :data:`VERIFY_ON_READ` is the global A/B toggle
  ``bench.py --integrity-overhead`` measures (gate: <2% p50).  Writers
  ALWAYS emit checksums; only read-side verification toggles.
- **corruption counters**: every detection increments
  ``yacy_storage_corruption_total{kind,action}`` via
  :func:`note_corruption`; quarantine actions (a corrupt run pulled
  from serving, the term answered from surviving generations) are the
  graceful path, ``error`` actions raised a typed exception to the
  caller.  The ``storage_corruption`` health rule goes critical on any
  new event, which dumps a flight-recorder incident on the edge.
- **torn-tail counters**: a journal replay that drops a torn final
  line (the expected kill−9 artifact) counts it per store
  (``yacy_journal_torn_tail_total{store}``) instead of logging only —
  the chaos harness and fleet digests can now SEE partial-write
  recoveries (ISSUE 10 satellite).

Typed errors: :class:`CorruptRunError` / :class:`CorruptSegmentError` /
:class:`CorruptJournalError` all extend :class:`CorruptionError`, so
callers can catch the storage class without fishing for struct/json/
mmap internals.
"""

from __future__ import annotations

import os
import threading
import zlib

# the read-side verification switch (bench --integrity-overhead A/B);
# checksums are always WRITTEN — only verification toggles
VERIFY_ON_READ = True


def set_verify_on_read(on: bool) -> None:
    global VERIFY_ON_READ
    VERIFY_ON_READ = bool(on)


def verify_on_read() -> bool:
    return VERIFY_ON_READ


class CorruptionError(Exception):
    """Base of every checksum/format corruption the storage layer
    detects — callers catch THIS, not struct/json/mmap internals."""


class CorruptRunError(CorruptionError):
    """A paged run (.dat/.tix pair) failed open-scrub or a span's
    read-time checksum — the run is quarantine material."""


class CorruptSegmentError(CorruptionError):
    """A colstore segment failed open-scrub or a column checksum."""


class CorruptDenseError(CorruptionError):
    """The dense vector snapshot (vectors.npy) failed its crc32 footer
    or does not parse — quarantine material (dense serving degrades to
    sparse-only boosts; embeddings are re-encodable from text_t, so
    nothing irrecoverable is lost)."""


class CorruptJournalError(CorruptionError, ValueError):
    """A journal record failed its line checksum / decode mid-file (a
    torn FINAL line is recovered and counted, never raised).  Also a
    ValueError: the metadata replay raised ValueError on mid-file
    damage before this type existed, and its callers/tests catch
    that."""


def crc32(data: bytes, prev: int = 0) -> int:
    return zlib.crc32(data, prev) & 0xFFFFFFFF


def crc_arrays(*arrays) -> int:
    """One crc over the raw bytes of several numpy arrays, in order —
    the per-term-span / per-column checksum."""
    c = 0
    for a in arrays:
        c = zlib.crc32(memoryview(a).cast("B"), c)
    return c & 0xFFFFFFFF


# -- journal line checksums --------------------------------------------------
# format: "<crc8hex> <payload>" where crc is over the payload bytes.
# Legacy lines (no prefix) parse as before — old journals stay readable.

def crc_line(payload: str) -> str:
    return f"{crc32(payload.encode('utf-8')):08x} {payload}"


def check_line(line: str) -> tuple[str, bool]:
    """(payload, ok).  A line without a crc prefix is legacy: returned
    verbatim with ok=True (no claim made).  A prefixed line returns its
    payload with ok = crc match (when VERIFY_ON_READ; else True)."""
    if len(line) > 9 and line[8] == " ":
        prefix = line[:8]
        try:
            want = int(prefix, 16)
        except ValueError:
            return line, True           # not a crc prefix: legacy line
        payload = line[9:]
        if VERIFY_ON_READ and crc32(payload.encode("utf-8")) != want:
            return payload, False
        return payload, True
    return line, True


# -- counters ----------------------------------------------------------------

_lock = threading.Lock()
_corruption: dict[tuple[str, str], int] = {}
_torn_tails: dict[str, int] = {}
_verified = 0

# zero-filled on /metrics so health rules and alert expressions always
# resolve (the no-dead-rules discipline)
CANONICAL_EVENTS = (
    ("run", "quarantined"),      # corrupt span/open: run pulled from serving
    ("run", "error"),            # open failed with no index to quarantine from
    ("segment", "error"),        # segment open-scrub failure (structural)
    ("segment", "served_degraded"),  # column content crc mismatch: data
    #                                  served anyway (no redundant
    #                                  generation exists), loudly counted
    ("journal", "error"),        # mid-file journal record checksum mismatch
    ("dense", "quarantined"),    # dense vector snapshot crc mismatch:
    #                              file quarantined, sparse-only serving
)
JOURNAL_STORES = ("metadata", "webgraph", "rwi", "frontier", "errors")


def note_corruption(kind: str, action: str) -> None:
    with _lock:
        _corruption[(kind, action)] = _corruption.get((kind, action), 0) + 1


def corruption_counts() -> dict:
    """(kind, action) -> count, zero-filled over CANONICAL_EVENTS."""
    with _lock:
        out = {ka: 0 for ka in CANONICAL_EVENTS}
        out.update(_corruption)
        return out


def corruption_total() -> int:
    with _lock:
        return sum(_corruption.values())


def repair_torn_tail(path: str, store: str) -> bool:
    """Truncate a journal's torn FINAL line (a file not ending in a
    newline is mid-append kill−9 debris) BEFORE replay/reopen.  Without
    this the journal is reopened in append mode and the next record is
    glued onto the partial line — corrupting an acked, fsync'd record
    on the following restart.  Backscans for the last newline (bounded
    chunks, no full read), truncates after it, counts the torn tail.
    Returns True when a repair happened."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    if size == 0:
        return False
    with open(path, "rb+") as f:
        f.seek(size - 1)
        if f.read(1) == b"\n":
            return False                    # clean tail: nothing torn
        pos = size
        cut = 0
        chunk = 1 << 16
        while pos > 0:
            lo = max(0, pos - chunk)
            f.seek(lo)
            buf = f.read(pos - lo)
            nl = buf.rfind(b"\n")
            if nl >= 0:
                cut = lo + nl + 1
                break
            pos = lo
        f.truncate(cut)
        f.flush()
        os.fsync(f.fileno())
    note_torn_tail(store)
    return True


def journal_lines(path: str, store: str):
    """THE shared journal replay scaffold: torn-tail repair, then a
    STREAMED read (one-line lookahead — a long-crawl host journal can
    be large and the old per-store loops never doubled startup RSS)
    splitting records on ``\\n`` ONLY (file iteration never splits on
    U+2028/U+2029/U+0085, which ``ensure_ascii=False`` payloads can
    legitimately contain), decoded with ``errors="replace"`` (a
    bit-flipped byte must become a crc-failing line, not an uncaught
    ``UnicodeDecodeError`` that refuses startup), crc verification per
    line, and the shared damage classification: a damaged FINAL line is
    the expected kill−9 artifact (torn tail, recovered + counted),
    damage earlier is real journal corruption (counted; the
    storage_corruption rule sees it).  Yields ``(payload, is_last)``
    for every intact line."""
    repair_torn_tail(path, store)

    def classify(line: str, is_last: bool):
        if not line.strip():
            return
        payload, ok = check_line(line)
        if not ok:
            if is_last:
                note_torn_tail(store)
            else:
                note_corruption("journal", "error")
            return
        yield payload, is_last

    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            prev: str | None = None
            for raw in f:
                if prev is not None:
                    yield from classify(prev, False)
                prev = raw.rstrip("\n")
            if prev is not None:
                yield from classify(prev, True)
    except OSError:
        return


def journal_records(path: str, store: str):
    """`journal_lines` + JSON decoding, classifying an undecodable
    payload exactly like a crc failure (torn tail if final, corruption
    otherwise).  Yields dict records."""
    import json
    for payload, is_last in journal_lines(path, store):
        try:
            yield json.loads(payload)
        except json.JSONDecodeError:
            if is_last:
                note_torn_tail(store)
            else:
                note_corruption("journal", "error")


def note_torn_tail(store: str) -> None:
    """A journal replay dropped a torn tail line (the expected kill−9
    artifact — recovered, visible, counted)."""
    with _lock:
        _torn_tails[store] = _torn_tails.get(store, 0) + 1


def torn_tail_counts() -> dict:
    with _lock:
        out = {s: 0 for s in JOURNAL_STORES}
        out.update(_torn_tails)
        return out


def note_verified(n: int = 1) -> None:
    """A checksum verification actually ran (the --integrity-overhead
    gate asserts the ON windows were not vacuous)."""
    global _verified
    with _lock:
        _verified += n


def verified_total() -> int:
    with _lock:
        return _verified


def reset_counters() -> None:
    """Test isolation only — production counters are monotonic."""
    global _verified
    with _lock:
        _corruption.clear()
        _torn_tails.clear()
        _verified = 0
