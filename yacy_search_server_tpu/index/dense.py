"""Dense vector store — per-segment doc embeddings aligned to docids.

The M7 hybrid-rerank companion of the metadata store: one growable
``[capacity, dim]`` float16 block (the device-transfer unit for the
rerank matmul), filled at ``store_document`` time by the segment's
encoder.  Persistence is one .npy snapshot rewritten on flush/close —
embeddings are derivable data (re-encodable from text_t), so a crash
loses nothing irrecoverable.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from ..ops.dense import DIM, ENCODER_VERSION


class DenseVectorStore:
    def __init__(self, data_dir: str | None = None, dim: int = DIM):
        self.dim = dim
        self.data_dir = data_dir
        self._vecs = np.zeros((256, dim), dtype=np.float16)
        self._n = 0
        self._lock = threading.Lock()
        self._dirty = 0
        self.stale_encoder = False
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            p = self._path()
            if os.path.isfile(p):
                loaded = np.load(p)
                if loaded.shape[1] == dim:
                    self._vecs = loaded.copy()
                    self._n = loaded.shape[0]
                # vectors hashed by an older encoder cannot be compared
                # with current query vectors; migration re-encodes
                self.stale_encoder = (self._n > 0 and
                                      self._load_version()
                                      != ENCODER_VERSION)

    def _path(self) -> str:
        return os.path.join(self.data_dir, "vectors.npy")

    def _version_path(self) -> str:
        return os.path.join(self.data_dir, "ENCODER_VERSION")

    def _load_version(self) -> int:
        try:
            with open(self._version_path(), encoding="ascii") as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return 1    # pre-versioning stores used the v1 FNV hash

    def put(self, docid: int, vec: np.ndarray) -> None:
        with self._lock:
            while docid >= self._vecs.shape[0]:
                self._vecs = np.vstack(
                    [self._vecs, np.zeros_like(self._vecs)])
            self._vecs[docid] = vec.astype(np.float16)
            self._n = max(self._n, docid + 1)
            self._dirty += 1
            if self.data_dir and self._dirty >= 512:
                self._save_locked()

    def get_block(self, docids: np.ndarray) -> np.ndarray:
        """[len(docids), dim] float16 gather (device-transfer unit)."""
        with self._lock:
            return self._vecs[np.asarray(docids, dtype=np.int64)]

    def __len__(self) -> int:
        return self._n

    def _save_locked(self) -> None:
        tmp = self._path() + ".tmp"
        with open(tmp, "wb") as f:
            np.save(f, self._vecs[:max(self._n, 1)])
        os.replace(tmp, self._path())
        # while the store is stale (migration in flight) the version
        # marker must NOT advance: a crash mid-re-encode would otherwise
        # mask the remaining v1 vectors as migrated forever
        if not self.stale_encoder:
            with open(self._version_path(), "w", encoding="ascii") as f:
                f.write(str(ENCODER_VERSION))
        self._dirty = 0

    def mark_encoder_current(self) -> None:
        """Called by the migration AFTER every vector was re-encoded:
        clears staleness and stamps the encoder version."""
        with self._lock:
            self.stale_encoder = False
            self._save_locked()

    def flush(self) -> None:
        if self.data_dir:
            with self._lock:
                self._save_locked()

    def close(self) -> None:
        self.flush()
