"""Dense vector store — per-segment doc embeddings aligned to docids.

The M7 hybrid-rerank companion of the metadata store: one growable
``[capacity, dim]`` float16 block (the device-transfer unit for the
rerank matmul), filled at ``store_document`` time by the segment's
encoder.  Persistence is one .npy snapshot rewritten on flush/close —
embeddings are derivable data (re-encodable from text_t), so a crash
loses nothing irrecoverable.
"""

from __future__ import annotations

import os
import struct
import threading

import numpy as np

from ..ops.dense import DIM, ENCODER_VERSION
from ..utils import profiling
from . import integrity

# crc footer on the vectors.npy snapshot (M84 discipline, ISSUE 11
# satellite): magic + little-endian u32 crc32 over the npy payload,
# appended AFTER the array (np.load reads exactly the header-declared
# bytes, so footer-free legacy files and footered files both load)
_FOOTER_MAGIC = b"YDV1"
_FOOTER_LEN = len(_FOOTER_MAGIC) + 4


class DenseVectorStore:
    # device-residency cap for the forward index: beyond it the rerank
    # path falls back to the host gather (a 1 GiB f16 block is ~2M docs
    # at dim 256 — past that the block belongs in the tiered-residency
    # work of ROADMAP item 4, not in one monolithic upload).  The
    # class attribute is the default; the serving knob is
    # index.dense.deviceBudgetBytes (instance device_budget_bytes).
    DEVICE_BUDGET_BYTES = 1 << 30
    # dirty-row bookkeeping cap for the device-block patch path (see
    # device_block): a set bigger than this costs more than the full
    # re-upload it would save
    _DIRTY_CAP = 1 << 16

    def __init__(self, data_dir: str | None = None, dim: int = DIM,
                 device_budget_bytes: int | None = None):
        self.dim = dim
        self.data_dir = data_dir
        self.device_budget_bytes = (self.DEVICE_BUDGET_BYTES
                                    if device_budget_bytes is None
                                    else int(device_budget_bytes))
        self._vecs = np.zeros((256, dim), dtype=np.float16)
        self._n = 0
        self._lock = threading.Lock()
        self._dirty = 0
        self.stale_encoder = False
        # vector-content version: bumps on EVERY write (put / re-encode)
        # — the hybrid top-k cache keys on it (plus ENCODER_VERSION), so
        # a cached hybrid answer can never survive a vector or encoder
        # change (the arena epoch only covers postings mutations)
        self.version = 0
        # device-resident forward index (the M7 rerank's doc-vector
        # block, resident like the postings arena): uploaded lazily,
        # re-uploaded when the content version moves; rows pad to a
        # pow2 bucket so compile shapes stay bounded
        self._fwd = None
        self._fwd_version = -1
        self._fwd_device = None
        # serializes uploads among device_block callers WITHOUT holding
        # the write lock across the device transfer: indexers keep
        # putting vectors while a (possibly seconds-long, through a
        # remote tunnel) re-upload is in flight
        self._fwd_lock = profiling.ObservedLock("dense_fwd")
        # rows written since the last device upload: device_block
        # scatters ONLY these into the resident block (indexing cadence
        # must not re-ship the whole index per query wave); None =
        # overflowed past _DIRTY_CAP, full re-upload on next access
        self._fwd_dirty: set | None = set()
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            p = self._path()
            if os.path.isfile(p):
                loaded = self._load_verified(p)
                if loaded is not None and loaded.shape[1] == dim:
                    self._vecs = loaded.copy()
                    self._n = loaded.shape[0]
                # vectors hashed by an older encoder cannot be compared
                # with current query vectors; migration re-encodes
                self.stale_encoder = (self._n > 0 and
                                      self._load_version()
                                      != ENCODER_VERSION)

    def _path(self) -> str:
        return os.path.join(self.data_dir, "vectors.npy")

    def _load_verified(self, p: str) -> np.ndarray | None:
        """Load the vector snapshot under the M84 read-side integrity
        discipline: a ``YDV1`` crc32 footer (written by _save_locked)
        is verified over the npy payload; a mismatch — or a snapshot
        torn/garbled beyond np.load — QUARANTINES the file (renamed
        ``.corrupt``) and returns None, so dense serving degrades to
        sparse-only boosts (zero vectors) instead of crashing the open.
        Footer-free legacy files load as before (no claim made).
        Counted in yacy_storage_corruption_total{kind="dense"}; the
        typed error (integrity.CorruptDenseError) is raised and caught
        here so callers that want the error surface can use
        _read_checked directly."""
        try:
            return self._read_checked(p)
        except (integrity.CorruptDenseError, OSError):
            integrity.note_corruption("dense", "quarantined")
            try:
                os.replace(p, p + ".corrupt")
            except OSError:
                pass
            return None

    @staticmethod
    def _read_checked(p: str) -> np.ndarray:
        """np.load + footer crc verification (streamed — no staging
        copy of the up-to-1-GiB snapshot); raises
        integrity.CorruptDenseError on a checksum mismatch or an
        unreadable snapshot."""
        try:
            arr = np.load(p, allow_pickle=False)
        except Exception as e:
            raise integrity.CorruptDenseError(
                f"dense snapshot does not parse as npy: {e!r}") from e
        try:
            with open(p, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size < _FOOTER_LEN:
                    return arr                       # legacy: no claim
                f.seek(size - _FOOTER_LEN)
                tail = f.read(_FOOTER_LEN)
                if tail[:len(_FOOTER_MAGIC)] != _FOOTER_MAGIC:
                    return arr                       # legacy: no claim
                if not integrity.verify_on_read():
                    return arr
                (want,) = struct.unpack("<I", tail[-4:])
                f.seek(0)
                crc = 0
                left = size - _FOOTER_LEN
                while left > 0:
                    chunk = f.read(min(1 << 22, left))
                    if not chunk:
                        break
                    left -= len(chunk)
                    crc = integrity.crc32(chunk, crc)
        except OSError as e:
            raise integrity.CorruptDenseError(
                f"dense snapshot unreadable: {e!r}") from e
        if crc != want:
            raise integrity.CorruptDenseError(
                f"dense snapshot crc mismatch: stored {want:#x}, "
                f"computed {crc:#x}")
        return arr

    def _version_path(self) -> str:
        return os.path.join(self.data_dir, "ENCODER_VERSION")

    def _load_version(self) -> int:
        try:
            with open(self._version_path(), encoding="ascii") as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return 1    # pre-versioning stores used the v1 FNV hash

    def put(self, docid: int, vec: np.ndarray) -> None:
        with self._lock:
            while docid >= self._vecs.shape[0]:
                self._vecs = np.vstack(
                    [self._vecs, np.zeros_like(self._vecs)])
            self._vecs[docid] = vec.astype(np.float16)
            self._n = max(self._n, docid + 1)
            self.version += 1
            self._dirty += 1
            if self._fwd_dirty is not None:
                self._fwd_dirty.add(docid)
                if len(self._fwd_dirty) > self._DIRTY_CAP:
                    # past the cap a full re-upload is cheaper than the
                    # bookkeeping; None = "patch set overflowed"
                    self._fwd_dirty = None
            if self.data_dir and self._dirty >= 512:
                self._save_locked()

    def get_block(self, docids: np.ndarray) -> np.ndarray:
        """[len(docids), dim] float16 gather (device-transfer unit).

        Docids without a stored vector gather zeros (zero boost), the
        same contract the device forward index gives pad rows — a
        postings row whose dense.put hasn't landed yet (or never will)
        must rank by its sparse score, not crash the hybrid query."""
        with self._lock:
            ids = np.asarray(docids, dtype=np.int64)
            out = np.zeros((len(ids), self.dim), np.float16)
            ok = (ids >= 0) & (ids < self._n)
            out[ok] = self._vecs[ids[ok]]
            return out

    def _rows_locked(self) -> int:
        # pow2 row bucket (>=256) — the ONE derivation shared by the
        # prewarm shape key and the uploaded block (divergence would
        # warm shapes device_block never dispatches)
        return 1 << max(8, (max(self._n, 1) - 1).bit_length())

    def device_rows(self) -> int:
        """The forward index's padded device row bucket (a compile-shape
        key for the devstore prewarm)."""
        with self._lock:
            return self._rows_locked()

    def device_block(self, device):
        """The device-resident forward index: ([rows, dim] float16 on
        `device`, content version) — or None when the block exceeds
        DEVICE_BUDGET_BYTES (callers fall back to the host gather).

        Block-resident like the postings arena: one upload serves every
        subsequent rerank dispatch, so the per-query host-side
        ``get_block`` gather + upload round trip disappears from the
        serving path. Stale on any vector write (the version moved);
        a stale block is PATCHED on device — only the rows written
        since the last upload cross the wire (a steady indexer must not
        cost one full-index transfer per query wave) — falling back to
        a wholesale re-upload when the row bucket grew, the dirty set
        overflowed, or more than a quarter of the block changed. Rows
        pad to a pow2 bucket (>=256) so a growing index mints a bounded
        set of compile shapes; docids past the bucket simply have no
        vector yet and the kernel scores them with zero boost."""
        import jax
        # lint: blocking-ok(serializing uploads is _fwd_lock's sole
        # purpose; the write lock is released for the transfer, so
        # indexers keep putting vectors while an upload is in flight)
        with self._fwd_lock:
            with self._lock:
                rows = self._rows_locked()
                if rows * self.dim * 2 > self.device_budget_bytes:
                    # release the last in-budget block: it can never be
                    # served again, and up to 1 GiB of pinned device
                    # memory would otherwise shadow the postings arena
                    # for the rest of the process
                    self._fwd = None
                    self._fwd_device = None
                    self._fwd_version = -1
                    return None
                if (self._fwd is not None
                        and self._fwd_version == self.version
                        and self._fwd_device is device
                        and self._fwd.shape[0] == rows):
                    return self._fwd, self._fwd_version
                # snapshot under the write lock, then release it for
                # the transfer: a put() racing the upload lands AFTER
                # `ver`, so the cached block is immediately stale and
                # the next call patches it in — but the indexer never
                # blocked on the transfer
                ver = self.version
                base, dirty = self._fwd, self._fwd_dirty
                patch = (base is not None and dirty is not None
                         and self._fwd_device is device
                         and base.shape[0] == rows
                         and 0 < len(dirty) <= rows // 4)
                if patch:
                    idx = np.fromiter(dirty, np.int64, len(dirty))
                    sub = self._vecs[idx]
                else:
                    buf = np.zeros((rows, self.dim), np.float16)
                    buf[:self._n] = self._vecs[:self._n]
                self._fwd_dirty = set()
            try:
                if patch:
                    # scatter only the dirty rows into the resident
                    # block; the index count pads to a pow2 bucket
                    # (bounded compile shapes) — pad lanes repeat idx[0]
                    # with its own row, so duplicate indices carry
                    # identical values
                    nb = 1 << max(4, (len(idx) - 1).bit_length())
                    pidx = np.full(nb, idx[0], np.int32)
                    pidx[:len(idx)] = idx
                    psub = np.repeat(sub[:1], nb, axis=0)
                    psub[:len(idx)] = sub
                    fwd = base.at[jax.device_put(pidx, device)].set(
                        jax.device_put(psub, device))
                else:
                    fwd = jax.device_put(buf, device)
            except BaseException:
                # a failed transfer must not LOSE the snapshotted dirty
                # rows: _fwd/_fwd_version are unchanged, so a later
                # patch would scatter only post-failure writes onto the
                # old base and serve these rows stale-as-fresh
                with self._lock:
                    if dirty is None or self._fwd_dirty is None:
                        self._fwd_dirty = None
                    else:
                        self._fwd_dirty |= dirty
                raise
            with self._lock:
                self._fwd = fwd
                self._fwd_version = ver
                self._fwd_device = device
            return fwd, ver

    def __len__(self) -> int:
        with self._lock:
            return self._n

    def _save_locked(self) -> None:
        tmp = self._path() + ".tmp"
        with open(tmp, "wb+") as f:
            np.save(f, self._vecs[:max(self._n, 1)])
            # crc32 footer over the npy payload, streamed back off the
            # just-written file (a BytesIO staging copy would double
            # peak RAM at the 1 GiB budget); verified at open
            # (_load_verified). Writers always emit the footer, only
            # read-side verification toggles (the M84 discipline).
            f.flush()
            f.seek(0)
            crc = 0
            while True:
                chunk = f.read(1 << 22)
                if not chunk:
                    break
                crc = integrity.crc32(chunk, crc)
            f.seek(0, os.SEEK_END)
            f.write(_FOOTER_MAGIC)
            f.write(struct.pack("<I", crc))
        os.replace(tmp, self._path())
        # while the store is stale (migration in flight) the version
        # marker must NOT advance: a crash mid-re-encode would otherwise
        # mask the remaining v1 vectors as migrated forever
        if not self.stale_encoder:
            with open(self._version_path(), "w", encoding="ascii") as f:
                f.write(str(ENCODER_VERSION))
        self._dirty = 0

    def mark_encoder_current(self) -> None:
        """Called by the migration AFTER every vector was re-encoded:
        clears staleness and stamps the encoder version."""
        with self._lock:
            self.stale_encoder = False
            self._save_locked()

    def flush(self) -> None:
        if self.data_dir:
            with self._lock:
                self._save_locked()

    def close(self) -> None:
        self.flush()
