"""Device-resident postings serving — queries rank placed blocks, not uploads.

The round-1 gap (VERDICT weak #1): the production read path re-uploaded its
candidate block to the device on every query; only the benchmark ran
against pre-placed arrays. This module realizes the declared design stance
(SURVEY.md §7.1 "postings live as dense device blocks") for the serving
path, mirroring the reference's IndexCell ram/array split (reference:
source/net/yacy/kelondro/rwi/IndexCell.java:65-283) with "array" meaning
immutable device-resident blocks:

- ``DeviceArena`` — one growable device buffer set (int16 features, int32
  flags, int32 docids) that frozen runs pack into once, at flush/merge
  time. Each (run, term) occupies a contiguous, tile-aligned extent, so a
  query addresses its candidates by (start, count) scalars: the per-query
  host->device traffic for a fully-merged term is a handful of scalars.
- a ``dead`` docid bitmap on device — tombstones apply as a gather in the
  kernel, so deletes never force repacking (immutable runs stay immutable;
  the RWI folds tombstones in at merge, after which the packed blocks are
  physically clean).
- the RAM-buffer delta (postings newer than the last flush) uploads per
  query as a small padded block (<= the flush threshold, typically a few
  hundred rows) merged into stats and top-k — the ram/array split.

The ranking kernel streams extents tile-by-tile through
``lax.fori_loop`` + ``lax.dynamic_slice`` with a running top-k carry (the
long-context streaming shape of ops/streaming.py), so ONE compilation
serves every span length; stats (min/max normalization bounds) accumulate
in a first pass over the same tiles, exactly reproducing the single-shot
kernel's semantics (ops/ranking.local_stats over the constraint-masked
candidate set — reference ReferenceOrder.normalizeWith,
source/net/yacy/search/ranking/ReferenceOrder.java:70-211).

Constraint filters that read posting features (contentdom flag, language,
daterange) evaluate inside the kernel from scalar parameters; queries
needing host-side data (site:/tld:/filetype: metadata checks, exclusion
terms, date-sort, authority-boosted profiles) fall back to the host path
in SearchEvent — eligibility is decided by ``DeviceSegmentStore.eligible``.
"""

from __future__ import annotations

import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.ranking import (cardinal_from_stats, compact_feats, local_stats)
from ..ops.streaming import merge_stats
from ..utils.eventtracker import EClass, update as track
from . import postings as P

# the kernel streams extents one TILE per step; extents themselves are NOT
# aligned — a tile read may overrun into neighbor rows (masked out by the
# in-span predicate), so the arena always keeps >= one spare tile of
# capacity past the used region to keep dynamic_slice in bounds
TILE = 32_768
# rows per packing upload (one compiled shape for bulk run packing)
PACK_CHUNK = 1 << 18
# delta/remainder blocks pad to buckets (bounds compile count)
_DELTA_BUCKETS = (256, 1024, 4096, 16_384, 65_536, 262_144)

NO_LANG = 0          # language filter sentinel (pack_language('') == 0)
NO_FLAG = -1         # contentdom flag sentinel
DAYS_NONE_LO = -(2 ** 30)
DAYS_NONE_HI = 2 ** 30
NEG_INF32 = -(2 ** 31 - 1)


def _bucket_delta(n: int) -> int:
    for b in _DELTA_BUCKETS:
        if n <= b:
            return b
    return ((n + TILE - 1) // TILE) * TILE




# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------

def _constraint_valid(f, fl, lang_filter, flag_bit, from_days, to_days):
    v = (lang_filter == NO_LANG) | (
        f[:, P.F_LANGUAGE].astype(jnp.int32) == lang_filter)
    v &= (flag_bit == NO_FLAG) | (((fl >> jnp.maximum(flag_bit, 0)) & 1) == 1)
    lastmod = f[:, P.F_LASTMOD].astype(jnp.int32)
    v &= (from_days == DAYS_NONE_LO) | (lastmod >= from_days)
    v &= (to_days == DAYS_NONE_HI) | (lastmod <= to_days)
    return v


def _tile_valid(dd, dead, base_valid):
    """Liveness: in-extent rows (docid >= 0) that are not tombstoned.

    Docids beyond the bitmap are alive by construction — the bitmap grows
    to cover every tombstoned docid (dead_array), so clipping must not
    alias them onto the last slot."""
    in_range = dd < dead.shape[0]
    hit = dead[jnp.clip(dd, 0, dead.shape[0] - 1)]
    return base_valid & (dd >= 0) & ~(hit & in_range)


@partial(jax.jit, static_argnames=("k", "n_spans", "with_delta"))
def _rank_spans_kernel(feats16, flags, docids, dead,
                       starts, counts,
                       d_feats16, d_flags, d_docids,
                       lang_filter, flag_bit, from_days, to_days,
                       norm_coeffs, flag_bits, flag_shifts,
                       domlength_coeff, tf_coeff, language_coeff,
                       authority_coeff, language_pref,
                       k: int, n_spans: int, with_delta: bool):
    """Score up to `n_spans` arena extents (+ an optional delta block) and
    return the global top-k. Two streamed passes: stats, then score+top-k.

    starts/counts: int32 [n_spans] extent descriptors (count 0 = unused).
    All shapes except the delta block are invariant across queries and
    index growth does not recompile (extents address into the same arrays).
    """
    def tile_of(span_start, span_count, i):
        off = span_start + i * TILE
        f = lax.dynamic_slice(feats16, (off, 0), (TILE, P.NF))
        fl = lax.dynamic_slice(flags, (off,), (TILE,))
        dd = lax.dynamic_slice(docids, (off,), (TILE,))
        in_span = jnp.arange(TILE) < (span_count - i * TILE)
        v = _tile_valid(dd, dead, in_span)
        v &= _constraint_valid(f, fl, lang_filter, flag_bit,
                               from_days, to_days)
        return f, fl, dd, v

    # -- pass 1: stats over every valid row ---------------------------------
    # (flags column is zeroed in the compact block; its min/max are masked
    # out by normalization — see the cardinal_scores16 note)
    def stats_of(f, v):
        return local_stats(f, v, jnp.zeros(f.shape[0], jnp.int32),
                           num_hosts=1, with_host_counts=False)

    def span_stats(carry, s):
        start, count = starts[s], counts[s]
        n_tiles = (count + TILE - 1) // TILE

        def body(i, st):
            f, fl, dd, v = tile_of(start, count, i)
            return merge_stats(st, stats_of(f, v))
        return lax.fori_loop(0, n_tiles, body, carry)

    big, small = jnp.int32(2 ** 31 - 1), jnp.int32(-(2 ** 31 - 1))
    stats = {"col_min": jnp.full((P.NF,), big),
             "col_max": jnp.full((P.NF,), small),
             "tf_min": jnp.float32(jnp.inf), "tf_max": jnp.float32(-jnp.inf),
             "host_counts": jnp.zeros((1,), jnp.int32)}
    for s in range(n_spans):
        stats = span_stats(stats, s)
    if with_delta:
        d_n = d_docids.shape[0]
        d_v = _tile_valid(d_docids, dead, jnp.ones(d_n, bool))
        d_v &= _constraint_valid(d_feats16, d_flags, lang_filter, flag_bit,
                                 from_days, to_days)
        d_st = stats_of(d_feats16, d_v)
        stats = merge_stats(stats, d_st)

    # -- pass 2: score tiles, merge running top-k ---------------------------
    def score_rows(f, fl, v):
        return cardinal_from_stats(f, v, jnp.zeros(f.shape[0], jnp.int32),
                                   stats, norm_coeffs, flag_bits, flag_shifts,
                                   domlength_coeff, tf_coeff, language_coeff,
                                   authority_coeff, language_pref,
                                   fast_div=True, flags=fl)

    def merge_topk(run, tile_s, tile_d):
        run_s, run_d = run
        s = jnp.concatenate([run_s, tile_s])
        d = jnp.concatenate([run_d, tile_d])
        top_s, idx = lax.top_k(s, k)
        return top_s, d[idx]

    init = (jnp.full((k,), NEG_INF32, jnp.int32), jnp.full((k,), -1, jnp.int32))

    def span_score(carry, s):
        start, count = starts[s], counts[s]
        n_tiles = (count + TILE - 1) // TILE

        def body(i, run):
            f, fl, dd, v = tile_of(start, count, i)
            sc = score_rows(f, fl, v)
            tile_s, tile_i = lax.top_k(sc, min(k, TILE))
            return merge_topk(run, tile_s, dd[tile_i])
        return lax.fori_loop(0, n_tiles, body, carry)

    run = init
    for s in range(n_spans):
        run = span_score(run, s)
    if with_delta:
        sc = score_rows(d_feats16, d_flags, d_v)
        tile_s, tile_i = lax.top_k(sc, min(k, sc.shape[0]))
        run = merge_topk(run, tile_s, d_docids[tile_i])
    return run


# ---------------------------------------------------------------------------
# The arena
# ---------------------------------------------------------------------------

def _reslab(chunks, slab: int):
    """Re-chunk a (docids, feats) stream into exact `slab`-row slabs plus
    one final remainder — thousands of tiny per-term chunks must not each
    become a device upload."""
    buf_d, buf_f, acc = [], [], 0
    for d, f in chunks:
        if not len(d):
            continue
        buf_d.append(np.asarray(d))
        buf_f.append(np.asarray(f))
        acc += len(d)
        while acc >= slab:
            D = np.concatenate(buf_d) if len(buf_d) > 1 else buf_d[0]
            F = np.concatenate(buf_f) if len(buf_f) > 1 else buf_f[0]
            yield D[:slab], F[:slab]
            buf_d, buf_f, acc = [D[slab:]], [F[slab:]], acc - slab
            if not acc:
                buf_d, buf_f = [], []
    if acc:
        yield (np.concatenate(buf_d) if len(buf_d) > 1 else buf_d[0],
               np.concatenate(buf_f) if len(buf_f) > 1 else buf_f[0])


# module-level jitted updaters (per-call lambdas would defeat the jit cache
# and recompile on every append). Deliberately NOT donated: a query thread
# may hold the previous buffer mid-dispatch, and donation would invalidate
# it under that thread — the copy-on-write costs one device-side arena copy
# per flush (rare), readers keep a consistent old or new buffer either way.
@jax.jit
def _write_rows2(buf, chunk, off):
    return lax.dynamic_update_slice(buf, chunk, (off, 0))


@jax.jit
def _write_rows1(buf, chunk, off):
    return lax.dynamic_update_slice(buf, chunk, (off,))


class DeviceArena:
    """Growable device buffers holding packed postings extents."""

    def __init__(self, device=None, budget_bytes: int = 2 << 30,
                 initial_rows: int = 4 * TILE):
        self.device = device or jax.devices()[0]
        self.budget_bytes = budget_bytes
        self._cap = initial_rows
        self._used = 0
        self._feats16 = self._dev(np.zeros((self._cap, P.NF), np.int16))
        self._flags = self._dev(np.zeros(self._cap, np.int32))
        self._docids = self._dev(np.full(self._cap, -1, np.int32))
        self._doc_cap = 1 << 16
        self._dead = self._dev(np.zeros(self._doc_cap, bool))
        self._pending_dead: list[int] = []

    def _dev(self, arr):
        return jax.device_put(arr, self.device)

    @staticmethod
    def row_bytes() -> int:
        return P.NF * 2 + 4 + 4

    @property
    def used_rows(self) -> int:
        return self._used

    @property
    def capacity_rows(self) -> int:
        return self._cap

    def bytes_used(self) -> int:
        return self._cap * self.row_bytes() + self._doc_cap

    def would_fit(self, rows: int) -> bool:
        need = self._used + rows + TILE
        new_cap = self._cap
        while new_cap < need:          # growth doubles: budget the real cap
            new_cap *= 2
        return new_cap * self.row_bytes() <= self.budget_bytes

    def _grow_to(self, rows: int) -> None:
        new_cap = self._cap
        while new_cap < rows:
            new_cap *= 2
        if new_cap == self._cap:
            return
        pad = new_cap - self._cap
        self._feats16 = jnp.pad(self._feats16, ((0, pad), (0, 0)))
        self._flags = jnp.pad(self._flags, (0, pad))
        self._docids = jnp.pad(self._docids, (0, pad), constant_values=-1)
        self._cap = new_cap

    def _write_chunk(self, docids: np.ndarray, feats: np.ndarray,
                     off: int, pad_to: int) -> None:
        n = len(docids)
        f16 = np.zeros((pad_to, P.NF), np.int16)
        fl = np.zeros(pad_to, np.int32)
        dd = np.full(pad_to, -1, np.int32)
        cf, cfl = compact_feats(np.ascontiguousarray(feats, dtype=np.int32))
        f16[:n], fl[:n], dd[:n] = cf, cfl, docids
        off = np.int32(off)
        self._feats16 = _write_rows2(self._feats16, self._dev(f16), off)
        self._flags = _write_rows1(self._flags, self._dev(fl), off)
        self._docids = _write_rows1(self._docids, self._dev(dd), off)

    def append_block(self, chunks) -> int:
        """Pack a flat block streamed as (docids, feats) numpy chunks;
        returns the block's base row. Incoming chunks of any shape are
        re-slabbed to PACK_CHUNK uploads (one compiled write shape) plus a
        bucket-padded remainder; pad rows carry docid -1 and are either
        overwritten by the next append or left inert past the used mark."""
        base = self._used
        for docids, feats in _reslab(chunks, PACK_CHUNK):
            n = len(docids)
            pad = n if n == PACK_CHUNK else _bucket_delta(n)
            self._grow_to(self._used + pad + TILE)
            self._write_chunk(docids, feats, self._used, pad)
            self._used += n
        return base

    def mark_dead(self, docid: int) -> None:
        self._pending_dead.append(docid)

    def dead_array(self):
        """The dead bitmap with pending tombstones applied (lazy batch)."""
        if self._pending_dead:
            idx = np.asarray(self._pending_dead, np.int32)
            hi = int(idx.max()) + 1
            if hi > self._doc_cap:
                new_cap = self._doc_cap
                while new_cap < hi:
                    new_cap *= 2
                self._dead = jnp.pad(self._dead, (0, new_cap - self._doc_cap))
                self._doc_cap = new_cap
            self._dead = self._dead.at[self._dev(idx)].set(True)
            self._pending_dead = []
        return self._dead

    def arrays(self):
        return self._feats16, self._flags, self._docids


class DeviceSegmentStore:
    """Span registry + query dispatch over a DeviceArena.

    Registered as the RWIIndex run listener: every flushed/merged run packs
    its terms into the arena once; queries then address extents by scalars.
    """

    MAX_SPANS = 8  # matches the RWI merge policy's max_runs

    def __init__(self, rwi, device=None, budget_bytes: int = 2 << 30):
        self.rwi = rwi
        self.arena = DeviceArena(device=device, budget_bytes=budget_bytes)
        # run path/id -> {termhash: (start, count)}
        self._packed: dict[int, dict[bytes, tuple[int, int]]] = {}
        self._lock = threading.RLock()
        self._consts = None
        self._profile_key = None
        self._garbage_rows = 0
        self.queries_served = 0
        self.fallbacks = 0
        # seed tombstones recorded before this store existed (restart path)
        for docid in rwi._tombstones:
            self.arena.mark_dead(docid)
        for run in list(rwi._runs):
            self.on_run_added(run)
        # attach LAST: if initial packing raises, the RWI must not be left
        # pointing at a half-initialized listener (flush would re-raise the
        # device error inside the indexing write path)
        rwi.listener = self

    # -- packing (listener protocol) ----------------------------------------

    def on_run_added(self, run) -> None:
        """Pack a frozen run into the arena as ONE flat block, reusing the
        run's own contiguous per-term layout (PagedRun .dat order); the
        term registry then addresses extents at block_base + term_start."""
        with self._lock:
            rid = id(run)
            if rid in self._packed:
                return
            rows = run.n_postings
            if rows == 0:
                self._packed[rid] = {}
                return
            if not self.arena.would_fit(rows):
                # over budget: run stays host-served (spans_for -> None for
                # its terms); merges may later shrink the index back in
                track(EClass.INDEX, "devstore_skip", rows)
                return
            base = self.arena.append_block(run.flat_chunks(PACK_CHUNK))
            self._packed[rid] = {
                th: (base + s, c) for th, (s, c) in run.all_spans().items()}
            track(EClass.INDEX, "devstore_pack", rows)

    def on_run_removed(self, run) -> None:
        with self._lock:
            spans = self._packed.pop(id(run), None)
            if spans:
                self._garbage_rows += sum(c for _, c in spans.values())
            # dead extents are reclaimed wholesale: once more than half the
            # arena is garbage (merges retire whole runs), rebuild it from
            # the live runs
            if (self._garbage_rows * 2 > max(self.arena.used_rows, 1)
                    and self._garbage_rows > 4 * TILE):
                self.repack()

    def on_run_swapped(self, old_run, new_run) -> None:
        """flush/merge swap FrozenRun -> PagedRun for the same rows: the
        extents stay valid, only the registry key moves."""
        with self._lock:
            spans = self._packed.pop(id(old_run), None)
            if spans is not None:
                # drops applied to the paged run during the swap window are
                # carried over by keying live terms only
                live = set(new_run.term_hashes())
                self._packed[id(new_run)] = {
                    th: ext for th, ext in spans.items() if th in live}

    def on_doc_deleted(self, docid: int) -> None:
        self.arena.mark_dead(docid)

    def on_term_dropped(self, run, termhash: bytes) -> None:
        with self._lock:
            spans = self._packed.get(id(run))
            if spans is not None:
                spans.pop(termhash, None)

    def live_rows(self) -> int:
        with self._lock:
            return sum(c for spans in self._packed.values()
                       for _, c in spans.values())

    def repack(self) -> None:
        """Rebuild the arena from live runs (reclaims dead extents). The
        tombstone bitmap carries over — deletes are independent of extent
        placement."""
        with self._lock:
            old = self.arena
            self._packed.clear()
            self.arena = DeviceArena(device=old.device,
                                     budget_bytes=old.budget_bytes)
            self.arena._dead = old._dead
            self.arena._doc_cap = old._doc_cap
            self.arena._pending_dead = old._pending_dead
            self._garbage_rows = 0
            for run in list(self.rwi._runs):
                self.on_run_added(run)

    # -- query dispatch ------------------------------------------------------

    def spans_for(self, termhash: bytes) -> list[tuple[int, int]] | None:
        """Arena extents covering ALL frozen postings of a term, oldest
        first — or None when any run holding the term is not packed."""
        with self._lock:
            out: list[tuple[int, int]] = []
            for run in list(self.rwi._runs):
                if not run.has(termhash):
                    continue
                spans = self._packed.get(id(run))
                if spans is None:
                    return None
                ext = spans.get(termhash)
                if ext is None:
                    return None
                out.append(ext)
            return out

    def _profile_consts(self, profile, language: str):
        key = (profile.to_external_string(), language)
        with self._lock:  # key and consts must publish atomically
            if self._profile_key != key:
                dev = self.arena.device
                put = lambda a: jax.device_put(np.asarray(a), dev)  # noqa: E731
                bits, shifts = profile.flag_coeffs()
                self._consts = (put(profile.norm_coeffs()), put(bits),
                                put(shifts),
                                put(np.int32(profile.domlength)),
                                put(np.int32(profile.tf)),
                                put(np.int32(profile.language)),
                                put(np.int32(profile.authority)),
                                put(np.int32(P.pack_language(language))))
                self._profile_key = key
            return self._consts

    def rank_term(self, termhash: bytes, profile, language: str = "en",
                  k: int = 100,
                  lang_filter: int = NO_LANG, flag_bit: int = NO_FLAG,
                  from_days: int | None = None, to_days: int | None = None):
        """Single-term ranked top-k from placed blocks (+ RAM delta upload).

        Returns (scores, docids, considered) best-first, or None when the
        term is not fully device-resident (caller falls back to the host
        path). `considered` counts candidate rows before tombstone and
        constraint masking (the SearchEvent accounting surface)."""
        # snapshot extents + arena buffers under one lock: a concurrent
        # repack() swaps the arena and remaps every extent, so the spans
        # must be read against the same buffers the kernel will scan
        with self._lock:
            spans = self.spans_for(termhash)
            if spans is None or len(spans) > self.MAX_SPANS:
                self.fallbacks += 1
                return None
            feats16, flags, docids = self.arena.arrays()
            dead = self.arena.dead_array()
        # RAM delta: the term's unflushed postings (ram/array split)
        with self.rwi._lock:
            delta = self.rwi._ram_postings(termhash)
        if not spans and delta is None:
            return np.empty(0, np.int32), np.empty(0, np.int32), 0
        considered = sum(c for _, c in spans) + (len(delta) if delta else 0)

        # per-query host args ride along with the ONE kernel dispatch (no
        # explicit device_puts: through a remote tunnel every separate
        # transfer is a full round trip, and the round trip IS the latency
        # floor — see BASELINE.md served-path notes)
        starts = np.zeros(self.MAX_SPANS, np.int32)
        counts = np.zeros(self.MAX_SPANS, np.int32)
        for i, (s, c) in enumerate(spans):
            starts[i], counts[i] = s, c
        with_delta = delta is not None and len(delta) > 0
        if with_delta:
            n = len(delta)
            b = _bucket_delta(n)
            df = np.zeros((b, P.NF), np.int16)
            dfl = np.zeros(b, np.int32)
            ddd = np.full(b, -1, np.int32)
            cf, cfl = compact_feats(delta.feats)
            df[:n], dfl[:n], ddd[:n] = cf, cfl, delta.docids
            d_args = (df, dfl, ddd)
        else:
            d_args = (np.zeros((1, P.NF), np.int16),
                      np.zeros(1, np.int32), np.full(1, -1, np.int32))

        consts = self._profile_consts(profile, language)
        kk = max(16, 1 << (max(k, 1) - 1).bit_length())  # bucket k: pow2
        out = _rank_spans_kernel(
            feats16, flags, docids, dead,
            starts, counts, *d_args,
            np.int32(lang_filter), np.int32(flag_bit),
            np.int32(DAYS_NONE_LO if from_days is None else from_days),
            np.int32(DAYS_NONE_HI if to_days is None else to_days),
            *consts, k=kk, n_spans=self.MAX_SPANS, with_delta=with_delta)
        s, d = jax.device_get(out)  # one combined fetch
        keep = (d >= 0) & (s > NEG_INF32)
        s, d = s[keep], d[keep]
        # cross-run duplicate docids are possible after raw transfer
        # re-pushes (rwi.get folds them host-side; here both rows scored):
        # keep the best-scored instance of each docid
        _, first = np.unique(d, return_index=True)
        if len(first) != len(d):
            sel = np.sort(first)
            s, d = s[sel], d[sel]
        self.queries_served += 1
        return s[:k], d[:k], considered
